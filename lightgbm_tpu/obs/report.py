"""Trace-file reporting — ``python -m lightgbm_tpu report ...``.

Subcommands:

  report <trace.jsonl> [--json]   TIMETAG-style single-trace summary
                                  (per-phase totals, per-iteration
                                  stats, compile/retrace accounting,
                                  memory watermarks)
  report merge <dir|files...>     cross-rank aggregation: aligns the
                                  per-rank JSONLs of one multi-host run
                                  on iteration boundaries and emits a
                                  per-phase per-rank timeline with
                                  straggler attribution (slowest-rank
                                  share, barrier-wait vs compute from
                                  the net.* spans)
  report diff <a.jsonl> <b.jsonl> first divergent record between two
                                  JSONL streams — built for the
                                  LIGHTGBM_TPU_AUDIT split-decision
                                  trail, where it pins the first
                                  divergent (iteration, leaf, feature,
                                  threshold, gain); exit 1 on
                                  divergence like diff(1)
  report costs <trace.jsonl>      HLO cost-model report: joins the
                                  ``jax_cost`` program inventory
                                  against measured phase spans into a
                                  per-phase efficiency table + "next
                                  kernel target" line (obs/costmodel)
  report bench-trend [dir]        BENCH_r*.json trajectory: per-round
                                  s/iter, dead-tunnel/fallback flags
                                  and gate verdicts as one table

Every subcommand takes ``--json`` for machine-readable output.

``summarize`` is also importable — bench.py uses it to fold a (possibly
partial) trace of a dead run into its failure report.  All loaders
tolerate torn/garbage lines (crash-cut traces) by skipping them with a
warning instead of raising.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional


def load_trace(path: str, warn: bool = True,
               rotated: bool = True) -> List[Dict[str, Any]]:
    """Read a JSONL trace, tolerating torn or garbage lines (the run
    died mid-write, or a crash truncated the tail) — partial traces are
    the point.  Skipped lines warn to stderr instead of raising.

    When the sink was size-rotated (LIGHTGBM_TPU_TRACE_MAX_MB), the
    older ``<path>.1`` generation is read first so the stream comes
    back in emission order."""
    paths = [path]
    if rotated and os.path.exists(path + ".1"):
        paths.insert(0, path + ".1")
    records = []
    skipped = 0
    for p in paths:
        with open(p) as f:
            for ln, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    skipped += 1
                    if warn:
                        sys.stderr.write(
                            f"warning: {p}:{ln}: skipping unparsable "
                            f"record (torn tail from a killed run?)\n"
                        )
                    continue
                if not isinstance(rec, dict):
                    skipped += 1
                    if warn:
                        sys.stderr.write(
                            f"warning: {p}:{ln}: skipping non-object "
                            f"record\n"
                        )
                    continue
                records.append(rec)
    return records


def net_bytes_by_purpose(records: List[Dict[str, Any]]) -> Dict[str, float]:
    """Total ``net.bytes`` counter value per purpose tag (``hist``,
    ``hist_q``, ``best_split``, ...) across a trace stream."""
    out: Dict[str, float] = {}
    for r in records:
        if r.get("ev") == "counter" and r.get("name") == "net.bytes":
            p = str(r.get("purpose", "misc"))
            out[p] = out.get(p, 0.0) + float(r.get("value", 0.0))
    return out


def quantized_wire_summary(purpose_bytes: Dict[str, float],
                           iters: int) -> Optional[Dict[str, Any]]:
    """Quantized-vs-f32 histogram payload accounting from the purpose
    ledger.  ``hist_q`` blobs are int16 (g,h) planes — by wire-format
    arithmetic the f32x3 payload for the SAME histograms is exactly 3x
    the bytes (F*B*12 vs F*B*4) — so the f32 equivalent is derivable
    without a second run.  Returns None when no histogram purpose was
    seen.  ``ratio`` is f32-equivalent over actually-sent histogram
    bytes: 1.0 for an unquantized run, approaching 3.0 when every
    histogram rides the quantized wire."""
    hq = purpose_bytes.get("hist_q", 0.0)
    hf = purpose_bytes.get("hist", 0.0)
    if hq <= 0 and hf <= 0:
        return None
    sent = hq + hf
    equiv = 3.0 * hq + hf
    n = max(iters, 1)
    return {
        "hist_q_bytes": int(hq),
        "hist_f32_bytes": int(hf),
        "hist_q_bytes_per_iter": round(hq / n, 1),
        "f32_equiv_bytes_per_iter": round(equiv / n, 1),
        "ratio": round(equiv / sent, 3) if sent > 0 else None,
    }


def summarize(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    spans: Dict[str, List[float]] = {}
    iters: List[Dict[str, Any]] = []
    compiles = 0
    compile_secs = 0.0
    retraces = 0
    peak_host = 0.0
    peak_dev = 0.0
    ingest_done: Dict[str, Any] = {}
    for r in records:
        ev = r.get("ev")
        if ev == "span":
            agg = spans.setdefault(r.get("name", "?"), [0.0, 0])
            agg[0] += float(r.get("dur_s", 0.0))
            agg[1] += 1
        elif ev == "iter":
            iters.append(r)
            peak_host = max(peak_host, float(r.get("host_rss_mb", 0.0)))
            peak_dev = max(peak_dev, float(r.get("dev_mb", 0.0)))
        elif ev == "event":
            name = r.get("name")
            if name == "jax_compile":
                compiles += 1
                compile_secs += float(r.get("secs", 0.0))
            elif name == "jax_retrace":
                retraces += 1
            elif name == "ingest.done":
                ingest_done = {k: v for k, v in r.items()
                               if k not in ("ev", "name", "ts")}
    phase_totals: Dict[str, Dict[str, float]] = {}
    for it in iters:
        for k, v in (it.get("phases") or {}).items():
            agg = phase_totals.setdefault(k, {"total_s": 0.0, "count": 0})
            agg["total_s"] += float(v)
            agg["count"] += 1
    walls = [float(it.get("wall_s", 0.0)) for it in iters]
    out = {
        "iterations": len(iters),
        "total_iter_wall_s": round(sum(walls), 6),
        "mean_s_per_iter": round(sum(walls) / len(walls), 6) if walls else None,
        "phases": {
            k: {"total_s": round(v["total_s"], 6), "count": v["count"],
                "mean_ms": round(1e3 * v["total_s"] / max(v["count"], 1), 3)}
            for k, v in sorted(phase_totals.items(),
                               key=lambda kv: -kv[1]["total_s"])
        },
        "spans": {
            k: {"total_s": round(t, 6), "count": c,
                "mean_ms": round(1e3 * t / max(c, 1), 3)}
            for k, (t, c) in sorted(spans.items(), key=lambda kv: -kv[1][0])
        },
        "compiles": compiles,
        "compile_secs": round(compile_secs, 3),
        "retraces_flagged": retraces,
        "peak_host_rss_mb": round(peak_host, 1),
        "peak_dev_mb": round(peak_dev, 1),
    }
    purpose_bytes = net_bytes_by_purpose(records)
    if purpose_bytes:
        out["net_bytes_by_purpose"] = {
            k: int(v) for k, v in sorted(purpose_bytes.items(),
                                         key=lambda kv: -kv[1])
        }
        qw = quantized_wire_summary(purpose_bytes, len(iters))
        if qw is not None:
            out["quantized_wire"] = qw
    if ingest_done:
        out["ingest"] = ingest_done
    if iters:
        last = iters[-1]
        out["last_iter"] = int(last.get("iter", -1))
        if "leaves" in last:
            out["leaves_last_iter"] = last["leaves"]
    return out


def top_phases_line(summary: Dict[str, Any], k: int = 3) -> str:
    """One-line per-phase percentage attribution — the top-``k`` phases
    by share of total phase time, e.g.
    ``top phases: partition 61.2% | histogram 22.4% | split 9.8%``.
    Shares are of the summed PHASE time (not iteration wall) so the line
    is meaningful for partial traces too.  Empty string when the trace
    has no phase records."""
    phases = summary.get("phases") or {}
    total = sum(v["total_s"] for v in phases.values())
    if not phases or total <= 0:
        return ""
    ranked = sorted(phases.items(), key=lambda kv: -kv[1]["total_s"])[:k]
    parts = [f"{name} {100.0 * v['total_s'] / total:.1f}%" for name, v in ranked]
    return "top phases: " + " | ".join(parts)


def render(summary: Dict[str, Any], path: str = "") -> str:
    """TIMETAG-style text table."""
    lines = []
    lines.append(f"=== lightgbm_tpu run-trace report{': ' + path if path else ''} ===")
    n = summary["iterations"]
    if n:
        lines.append(
            f"iterations: {n}   iter wall total: {summary['total_iter_wall_s']:.3f} s"
            f"   mean: {1e3 * summary['mean_s_per_iter']:.2f} ms/iter"
        )
    else:
        lines.append("iterations: 0 (no iter records — run died before training?)")
    total_wall = summary["total_iter_wall_s"] or 0.0
    if summary["phases"]:
        # one-line attribution: top-3 phases by share of iteration wall,
        # so "where does the time go" doesn't require reading the table
        # (or the raw JSONL)
        top = top_phases_line(summary)
        if top:
            lines.append(top)
        lines.append("")
        lines.append(f"{'phase (per-iteration)':<28}{'total_s':>10}{'count':>8}"
                     f"{'mean_ms':>10}{'% iter':>8}")
        for name, s in summary["phases"].items():
            pct = 100.0 * s["total_s"] / total_wall if total_wall else 0.0
            lines.append(f"{name:<28}{s['total_s']:>10.3f}{s['count']:>8}"
                         f"{s['mean_ms']:>10.2f}{pct:>8.1f}")
    if summary["spans"]:
        lines.append("")
        lines.append(f"{'span':<28}{'total_s':>10}{'count':>8}{'mean_ms':>10}")
        for name, s in list(summary["spans"].items())[:20]:
            lines.append(f"{name:<28}{s['total_s']:>10.3f}{s['count']:>8}"
                         f"{s['mean_ms']:>10.2f}")
    lines.append("")
    lines.append(
        f"compiles: {summary['compiles']} ({summary['compile_secs']:.1f} s)"
        f"   unexpected retraces flagged: {summary['retraces_flagged']}"
    )
    lines.append(
        f"memory watermarks: host RSS {summary['peak_host_rss_mb']:.0f} MB"
        + (f", device {summary['peak_dev_mb']:.0f} MB"
           if summary["peak_dev_mb"] else "")
    )
    qw = summary.get("quantized_wire")
    if qw:
        ratio = qw.get("ratio")
        lines.append(
            "histogram wire: "
            f"quantized {qw['hist_q_bytes_per_iter']:.0f} B/iter, "
            f"f32-equivalent {qw['f32_equiv_bytes_per_iter']:.0f} B/iter"
            + (f" ({ratio:.2f}x payload reduction)"
               if ratio is not None else "")
        )
    ing = summary.get("ingest")
    if ing:
        lines.append(
            "streaming ingest: "
            f"{ing.get('rows', '?')} rows in {ing.get('wall_s', '?')} s "
            f"({ing.get('rows_per_s', '?')} rows/s), "
            f"{ing.get('chunks_pass2', '?')} chunks x {ing.get('chunk_rows', '?')} rows, "
            f"packed {ing.get('packed_mb', '?')} MB, "
            f"peak RSS {ing.get('rss_peak_mb', '?')} MB"
        )
    return "\n".join(lines) + "\n"


# ----------------------------------------------------------------------
# cross-rank merge (report merge <dir|files...>)
# ----------------------------------------------------------------------
def _rank_of(records: List[Dict[str, Any]], fallback: int) -> int:
    for r in records:
        if "rank" in r:
            return int(r["rank"])
    return fallback


def load_rank_traces(paths: List[str]) -> Dict[int, List[Dict[str, Any]]]:
    """Load per-rank trace files into {rank: records}.  Rank comes from
    the records themselves (the tracer stamps ``rank`` in multi-rank
    runs); files without a rank field fall back to their argument
    order, with a warning."""
    by_rank: Dict[int, List[Dict[str, Any]]] = {}
    for i, p in enumerate(sorted(paths)):
        recs = load_trace(p)
        rank = _rank_of(recs, fallback=i)
        if not any("rank" in r for r in recs):
            sys.stderr.write(
                f"warning: {p}: records carry no rank field; assuming "
                f"rank {rank} from argument order\n"
            )
        if rank in by_rank:
            sys.stderr.write(
                f"warning: {p}: duplicate rank {rank}; concatenating\n"
            )
            by_rank[rank].extend(recs)
        else:
            by_rank[rank] = recs
    return by_rank


def _iter_wait_s(phases: Dict[str, float]) -> float:
    """Barrier-wait attributed inside one iteration record.  net.barrier
    spans nest a net.allgather span and BOTH accumulate into the phases
    map, so take the max of the pair rather than their sum."""
    return max(float(phases.get("net.barrier", 0.0)),
               float(phases.get("net.allgather", 0.0)))


def _rank_net_wait_s(records: List[Dict[str, Any]]) -> float:
    """Total barrier/collective wait from this rank's span records:
    top-level net.barrier spans plus net.allgather spans that are NOT
    nested inside a barrier (double-count guard via the parent field)."""
    total = 0.0
    for r in records:
        if r.get("ev") != "span":
            continue
        name = r.get("name", "")
        if name == "net.barrier":
            total += float(r.get("dur_s", 0.0))
        elif name == "net.allgather" and r.get("parent") != "net.barrier":
            total += float(r.get("dur_s", 0.0))
    return total


def merge_summary(by_rank: Dict[int, List[Dict[str, Any]]]) -> Dict[str, Any]:
    """Cross-rank aggregation aligned on iteration boundaries.

    Per rank and per common iteration (present on EVERY rank — torn
    tails shrink the aligned window rather than skewing it):
    ``wall_s`` and its split into ``wait_s`` (the net.barrier /
    net.allgather share of the iteration) and ``compute_s`` (the rest).
    The straggler is the rank with the largest aligned compute total;
    ``slowest_rank_share`` is its share of fleet compute, and
    ``wait_behind_straggler_s`` is what every other rank spent parked
    in barriers — the time a rebalance could reclaim (ROADMAP item 3).
    """
    ranks = sorted(by_rank)
    run_ids = {r.get("run_id") for recs in by_rank.values()
               for r in recs if r.get("run_id") is not None}
    worlds = {int(r["world"]) for recs in by_rank.values()
              for r in recs if "world" in r}
    if len(run_ids) > 1:
        sys.stderr.write(
            f"warning: traces carry {len(run_ids)} distinct run_ids "
            f"{sorted(map(str, run_ids))} — are these files from one run?\n"
        )
    iters: Dict[int, Dict[int, Dict[str, float]]] = {}  # rank -> it -> rec
    phases: Dict[str, Dict[int, float]] = {}            # phase -> rank -> s
    for rank in ranks:
        per_it: Dict[int, Dict[str, float]] = {}
        for r in by_rank[rank]:
            if r.get("ev") != "iter":
                continue
            it = int(r.get("iter", -1))
            ph = r.get("phases") or {}
            wall = float(r.get("wall_s", 0.0))
            wait = min(_iter_wait_s(ph), wall)
            per_it[it] = {"wall_s": wall, "wait_s": wait,
                          "compute_s": wall - wait,
                          "net_bytes": float(r.get("net_bytes", 0.0))}
            for name, dur in ph.items():
                phases.setdefault(name, {})
                phases[name][rank] = phases[name].get(rank, 0.0) + float(dur)
        iters[rank] = per_it
    common = sorted(set.intersection(*(set(iters[r]) for r in ranks))
                    if ranks else set())
    timeline = []
    for it in common:
        walls = {r: iters[r][it]["wall_s"] for r in ranks}
        computes = {r: iters[r][it]["compute_s"] for r in ranks}
        slowest = max(ranks, key=lambda r: computes[r])
        timeline.append({
            "iter": it,
            "wall_s": {r: round(walls[r], 6) for r in ranks},
            "compute_s": {r: round(computes[r], 6) for r in ranks},
            "wait_s": {r: round(iters[r][it]["wait_s"], 6) for r in ranks},
            "slowest_rank": slowest,
        })
    per_rank = {}
    for rank in ranks:
        wall = sum(iters[rank][it]["wall_s"] for it in common)
        wait = sum(iters[rank][it]["wait_s"] for it in common)
        nbytes = sum(iters[rank][it]["net_bytes"] for it in common)
        per_rank[rank] = {
            "iterations": len(iters[rank]),
            "aligned_iterations": len(common),
            "wall_s": round(wall, 6),
            "compute_s": round(wall - wait, 6),
            "barrier_wait_s": round(wait, 6),
            "net_wait_total_s": round(_rank_net_wait_s(by_rank[rank]), 6),
            "net_bytes": int(nbytes),
            "bytes_per_iter": round(nbytes / len(common), 1) if common
            else 0.0,
        }
        # quantized-training wire accounting: per-rank histogram-payload
        # ratio (f32-equivalent / sent; 1.0 = unquantized, ->3.0 = fully
        # quantized) from the purpose-tagged net.bytes counters
        qw = quantized_wire_summary(
            net_bytes_by_purpose(by_rank[rank]), len(common))
        if qw is not None:
            per_rank[rank]["hist_q_bytes"] = qw["hist_q_bytes"]
            per_rank[rank]["quantized_ratio"] = qw["ratio"]
        # out-of-core streaming accounting (boosting/ooc.py gauges): how
        # long this rank's folds sat stalled on its prefetch ring —
        # attributes streaming stragglers the way barrier_wait_s
        # attributes compute stragglers
        ooc_stall = ooc_fetch = 0.0
        saw_ooc = False
        for r in by_rank[rank]:
            if r.get("ev") != "gauge":
                continue
            if r.get("name") == "ooc.stall_ms":
                ooc_stall += float(r.get("value", 0.0))
                saw_ooc = True
            elif r.get("name") == "ooc.fetch_ms":
                ooc_fetch += float(r.get("value", 0.0))
                saw_ooc = True
        if saw_ooc:
            per_rank[rank]["ooc_stall_s"] = round(ooc_stall / 1e3, 6)
            per_rank[rank]["ooc_fetch_s"] = round(ooc_fetch / 1e3, 6)
            per_rank[rank]["ooc_stall_share"] = (
                round(ooc_stall / (wall * 1e3), 4) if wall > 0 else None)
    out: Dict[str, Any] = {
        "ranks": ranks,
        "world_size": (sorted(worlds)[-1] if worlds else len(ranks)),
        "run_id": (sorted(map(str, run_ids))[0] if len(run_ids) == 1
                   else None),
        "aligned_iterations": len(common),
        "per_rank": per_rank,
        "phases": {
            name: {r: round(v, 6) for r, v in sorted(vals.items())}
            for name, vals in sorted(
                phases.items(),
                key=lambda kv: -sum(kv[1].values()))
        },
        "timeline": timeline,
    }
    if ranks and common:
        compute = {r: per_rank[r]["compute_s"] for r in ranks}
        total_compute = sum(compute.values())
        straggler = max(ranks, key=lambda r: compute[r])
        slowest_counts = [t["slowest_rank"] for t in timeline]
        out["straggler"] = {
            "rank": straggler,
            "slowest_rank_share": round(
                compute[straggler] / total_compute, 4
            ) if total_compute > 0 else None,
            "slowest_in_iters": slowest_counts.count(straggler),
            "wait_behind_straggler_s": round(
                sum(per_rank[r]["barrier_wait_s"]
                    for r in ranks if r != straggler), 6),
        }
    # shard-rebalance events (rebalance.plan, boosting/gbdt.py): per-rank
    # rows owned before/after each move, plus the fleet barrier-wait
    # share on either side of it — did the move actually reclaim wait?
    # Every rank emits the identical event; dedupe on the iteration.
    events: Dict[int, Dict[str, Any]] = {}
    for recs in by_rank.values():
        for r in recs:
            if r.get("ev") == "event" and r.get("name") == "rebalance.plan":
                events.setdefault(int(r.get("iter", -1)), r)
    if events:
        def _wait_share(its):
            wall = sum(iters[r][it]["wall_s"] for r in ranks for it in its)
            wait = sum(iters[r][it]["wait_s"] for r in ranks for it in its)
            return round(wait / wall, 4) if wall > 0 else None

        out["rebalance"] = []
        for ev_it in sorted(events):
            ev = events[ev_it]
            out["rebalance"].append({
                "iter": ev_it,
                "rows_before": [int(c) for c in ev.get("before", [])],
                "rows_after": [int(c) for c in ev.get("after", [])],
                "wait_share_before": _wait_share(
                    [it for it in common if it < ev_it]),
                "wait_share_after": _wait_share(
                    [it for it in common if it >= ev_it]),
            })
    return out


def render_merge(m: Dict[str, Any]) -> str:
    lines = []
    rid = f" run_id={m['run_id']}" if m.get("run_id") else ""
    lines.append(
        f"=== lightgbm_tpu cross-rank report: {len(m['ranks'])} rank(s), "
        f"world={m['world_size']}, {m['aligned_iterations']} aligned "
        f"iteration(s){rid} ===")
    ranks = m["ranks"]
    # quantized-wire column only when some rank exchanged histograms;
    # OOC stall column only when some rank streamed its bin matrix
    show_q = any("quantized_ratio" in m["per_rank"][r] for r in ranks)
    show_ooc = any("ooc_stall_s" in m["per_rank"][r] for r in ranks)
    lines.append("")
    lines.append(f"{'rank':<8}{'iters':>7}{'wall_s':>10}{'compute_s':>11}"
                 f"{'barrier_wait_s':>16}"
                 + (f"{'ooc_stall_s':>13}{'stall%':>8}" if show_ooc else "")
                 + f"{'bytes/iter':>12}"
                 + (f"{'q_ratio':>9}" if show_q else ""))
    for r in ranks:
        pr = m["per_rank"][r]
        qr = pr.get("quantized_ratio")
        os_ = pr.get("ooc_stall_s")
        osh = pr.get("ooc_stall_share")
        lines.append(f"{r:<8}{pr['aligned_iterations']:>7}"
                     f"{pr['wall_s']:>10.3f}{pr['compute_s']:>11.3f}"
                     f"{pr['barrier_wait_s']:>16.3f}"
                     + (((f"{os_:>13.3f}" if os_ is not None
                          else f"{'-':>13}")
                         + (f"{100.0 * osh:>7.1f}%" if osh is not None
                            else f"{'-':>8}"))
                        if show_ooc else "")
                     + f"{pr.get('bytes_per_iter', 0.0):>12.0f}"
                     + ((f"{qr:>9.2f}" if qr is not None else f"{'-':>9}")
                        if show_q else ""))
    st = m.get("straggler")
    if st:
        share = st["slowest_rank_share"]
        share_txt = f"{100.0 * share:.1f}% of fleet compute" \
            if share is not None else "n/a"
        lines.append("")
        lines.append(
            f"straggler: rank {st['rank']} — {share_txt}, slowest in "
            f"{st['slowest_in_iters']}/{m['aligned_iterations']} "
            f"iteration(s); other ranks spent "
            f"{st['wait_behind_straggler_s']:.3f} s in barrier wait")
    if m.get("rebalance"):
        lines.append("")
        lines.append(f"{'rebalance':<14}{'rows/rank before -> after':<40}"
                     f"{'wait share':>14}")
        for ev in m["rebalance"]:
            wb, wa = ev["wait_share_before"], ev["wait_share_after"]
            trend = (f"{wb:.2f} -> {wa:.2f}"
                     if wb is not None and wa is not None else "n/a")
            lines.append(
                f"{'@ iter ' + str(ev['iter']):<14}"
                f"{str(ev['rows_before']) + ' -> ' + str(ev['rows_after']):<40}"
                f"{trend:>14}")
    if m["phases"]:
        lines.append("")
        header = f"{'phase':<24}" + "".join(f"rank{r:>2}/s{'':>3}"
                                            for r in ranks)
        lines.append(header)
        for name, vals in m["phases"].items():
            row = f"{name:<24}" + "".join(
                f"{vals.get(r, 0.0):>10.3f}" for r in ranks)
            lines.append(row)
    return "\n".join(lines) + "\n"


def merge_main(argv: List[str]) -> int:
    import glob
    import os

    args = [a for a in argv if not a.startswith("--")]
    as_json = "--json" in argv
    if not args:
        sys.stderr.write(
            "usage: python -m lightgbm_tpu report merge <dir|trace.jsonl...>"
            " [--json]\n")
        return 2
    paths: List[str] = []
    for a in args:
        if os.path.isdir(a):
            paths.extend(p for p in glob.glob(os.path.join(a, "*.jsonl"))
                         if not p.endswith(".crash.jsonl"))
        else:
            paths.append(a)
    if not paths:
        sys.stderr.write(f"no trace files found under {args}\n")
        return 1
    try:
        by_rank = load_rank_traces(paths)
    except OSError as e:
        sys.stderr.write(f"cannot read traces: {e}\n")
        return 1
    m = merge_summary(by_rank)
    if as_json:
        sys.stdout.write(json.dumps(m) + "\n")
    else:
        sys.stdout.write(render_merge(m))
    return 0


# ----------------------------------------------------------------------
# stream diff (report diff a.jsonl b.jsonl) — audit-trail divergence
# ----------------------------------------------------------------------
def first_divergence(
    a: List[Dict[str, Any]], b: List[Dict[str, Any]]
) -> Optional[Dict[str, Any]]:
    """First record index where the two streams differ, with the
    differing fields; None when identical.  A shorter stream diverges
    at its end (record=None on the truncated side)."""
    for i in range(max(len(a), len(b))):
        ra = a[i] if i < len(a) else None
        rb = b[i] if i < len(b) else None
        if ra == rb:
            continue
        fields = []
        if ra is not None and rb is not None:
            for k in sorted(set(ra) | set(rb)):
                if ra.get(k) != rb.get(k):
                    fields.append(k)
        return {"index": i, "a": ra, "b": rb, "fields": fields}
    return None


def render_divergence(div: Dict[str, Any], pa: str, pb: str) -> str:
    a, b = div["a"], div["b"]
    lines = [f"streams diverge at record {div['index']}:"]
    if a is None or b is None:
        short, path = ("a", pa) if a is None else ("b", pb)
        lines.append(f"  {short} ({path}) ends early; the other stream "
                     f"continues with: {json.dumps(b if a is None else a)}")
        return "\n".join(lines) + "\n"
    ctx = {k: a[k] for k in ("ev", "it", "k", "s", "leaf") if k in a}
    if ctx:
        lines.append("  at " + " ".join(f"{k}={v}" for k, v in ctx.items()))
    for k in div["fields"]:
        va, vb = a.get(k), b.get(k)
        if (isinstance(va, list) and isinstance(vb, list)
                and len(va) == len(vb)):
            # per-leaf value arrays: name the first differing index
            # instead of dumping two full vectors
            for i, (xa, xb) in enumerate(zip(va, vb)):
                if xa != xb:
                    lines.append(f"  {k}[{i}]: a={json.dumps(xa)}  "
                                 f"b={json.dumps(xb)}")
            continue
        lines.append(f"  {k}: a={json.dumps(va)}  b={json.dumps(vb)}")
    return "\n".join(lines) + "\n"


def diff_main(argv: List[str]) -> int:
    args = [a for a in argv if not a.startswith("--")]
    as_json = "--json" in argv
    if len(args) != 2:
        sys.stderr.write(
            "usage: python -m lightgbm_tpu report diff <a.jsonl> <b.jsonl>"
            " [--json]\n")
        return 2
    pa, pb = args
    try:
        a = load_trace(pa)
        b = load_trace(pb)
    except OSError as e:
        sys.stderr.write(f"cannot read stream: {e}\n")
        return 2
    div = first_divergence(a, b)
    if div is None:
        sys.stdout.write(
            json.dumps({"identical": True, "records": len(a)}) + "\n"
            if as_json else
            f"streams identical ({len(a)} records)\n")
        return 0
    if as_json:
        sys.stdout.write(json.dumps({"identical": False, **div}) + "\n")
    else:
        sys.stdout.write(render_divergence(div, pa, pb))
    return 1


# ----------------------------------------------------------------------
# cost-model report (report costs <trace.jsonl>) — obs/costmodel.py join
# ----------------------------------------------------------------------
def costs_main(argv: List[str]) -> int:
    from . import costmodel

    args = [a for a in argv if not a.startswith("--")]
    as_json = "--json" in argv
    if len(args) != 1:
        sys.stderr.write(
            "usage: python -m lightgbm_tpu report costs <trace.jsonl>"
            " [--json]\n")
        return 2
    path = args[0]
    try:
        records = load_trace(path)
    except OSError as e:
        sys.stderr.write(f"cannot read trace {path}: {e}\n")
        return 1
    summary = costmodel.costs_summary(records)
    if as_json:
        sys.stdout.write(json.dumps(summary) + "\n")
    else:
        sys.stdout.write(costmodel.render_costs(summary, path))
    return 0


# ----------------------------------------------------------------------
# bench trajectory (report bench-trend [dir]) — BENCH_r*.json history
# ----------------------------------------------------------------------
def load_bench_rounds(bench_dir: str) -> List[Any]:
    """[(basename, doc), ...] for every parseable BENCH_r*.json in
    ``bench_dir``, in round order."""
    out = []
    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            sys.stderr.write(f"warning: skipping unparsable {path}\n")
            continue
        if isinstance(doc, dict):
            out.append((os.path.basename(path), doc))
    return out


def _gate_verdict(parsed: Dict[str, Any]) -> str:
    """One-word verdict from a capture's gate annotations (bench.py
    apply_regression_gate): FAIL:<legs> when any regression_* flag is
    set, pass when at least one gate_* section was evaluated, '-' when
    nothing gated (first capture of a config, or gate opted out)."""
    def _leg(k):
        return "s_per_iter" if k == "regression" else k[len("regression_"):]

    regs = sorted(k for k, v in parsed.items()
                  if k.startswith("regression") and v)
    if regs:
        return "FAIL:" + ",".join(_leg(k) for k in regs)
    if any(k.startswith("gate") for k in parsed):
        return "pass"
    return "-"


def bench_trend_summary(rounds: List[Any]) -> Dict[str, Any]:
    """Per-round trajectory of the driver-captured bench history:
    metric/value/unit, backend-fallback (dead-tunnel) flag and gate
    verdict per round, plus a per-metric series with the best round —
    the table form of what previously only lived in raw JSON."""
    rows: List[Dict[str, Any]] = []
    for name, doc in rounds:
        parsed = doc.get("parsed")
        if not isinstance(parsed, dict):
            # tolerate raw bench-format files ({"metric": ...} at top)
            parsed = doc if "metric" in doc else None
        m = re.match(r"BENCH_(r\d+)", name)
        row: Dict[str, Any] = {
            "round": m.group(1) if m else name,
            "file": name,
            "rc": doc.get("rc"),
        }
        if parsed is None:
            row["parsed"] = False
            rows.append(row)
            continue
        row.update({
            "parsed": True,
            "metric": parsed.get("metric"),
            "value": parsed.get("value"),
            "unit": parsed.get("unit"),
            "vs_baseline": parsed.get("vs_baseline"),
            "device": parsed.get("device"),
            "backend_fallback": bool(parsed.get("backend_fallback")),
            "gate_verdict": _gate_verdict(parsed),
        })
        rows.append(row)
    by_metric: Dict[str, List[Dict[str, Any]]] = {}
    for row in rows:
        if row.get("parsed") and isinstance(row.get("value"), (int, float)):
            by_metric.setdefault(str(row["metric"]), []).append({
                "round": row["round"],
                "value": row["value"],
                "backend_fallback": row["backend_fallback"],
            })
    trends = {}
    for metric, pts in by_metric.items():
        best = min(pts, key=lambda p: p["value"])
        trends[metric] = {
            "points": pts,
            "first": pts[0],
            "last": pts[-1],
            "best": best,
        }
    return {"rounds": rows, "by_metric": trends}


def render_bench_trend(t: Dict[str, Any], bench_dir: str = "") -> str:
    rows = t["rounds"]
    lines = [
        f"=== lightgbm_tpu bench trend"
        f"{': ' + bench_dir if bench_dir else ''} "
        f"({len(rows)} round(s)) ==="]
    lines.append("")
    lines.append(f"{'round':<7}{'value':>10}{' unit':<8}{'vs_base':>9}"
                 f"{'backend':<17}{'gate':<22}metric")
    for r in rows:
        if not r.get("parsed"):
            lines.append(f"{r['round']:<7}{'-':>10}{'':<8}{'-':>9}"
                         f"{'-':<17}{'-':<22}"
                         f"(unparsed; rc={r.get('rc')})")
            continue
        val = f"{r['value']:.4f}" if isinstance(
            r.get("value"), (int, float)) else "-"
        vsb = f"{r['vs_baseline']:.2f}x" if isinstance(
            r.get("vs_baseline"), (int, float)) else "-"
        dev = str(r.get("device") or "-")
        if r.get("backend_fallback"):
            dev += " [fallback]"
        metric = str(r.get("metric") or "-")
        if len(metric) > 46:
            metric = metric[:43] + "..."
        lines.append(f"{r['round']:<7}{val:>10}{' ' + str(r.get('unit') or ''):<8}"
                     f"{vsb:>9}{dev[:16]:<17}{r['gate_verdict'][:21]:<22}"
                     f"{metric}")
    for metric, tr in t["by_metric"].items():
        if len(tr["points"]) < 2:
            continue
        first, last, best = tr["first"], tr["last"], tr["best"]
        speedup = (first["value"] / last["value"]
                   if last["value"] > 0 else None)
        short = metric if len(metric) <= 46 else metric[:43] + "..."
        lines.append("")
        lines.append(
            f"trend [{short}]: {first['round']} {first['value']:.4f} -> "
            f"{last['round']} {last['value']:.4f}"
            + (f" ({speedup:.2f}x vs first)" if speedup else "")
            + f"; best {best['round']} {best['value']:.4f}")
    return "\n".join(lines) + "\n"


def bench_trend_main(argv: List[str]) -> int:
    args = [a for a in argv if not a.startswith("--")]
    as_json = "--json" in argv
    if len(args) > 1:
        sys.stderr.write(
            "usage: python -m lightgbm_tpu report bench-trend [dir]"
            " [--json]\n")
        return 2
    # default: the repo root (where the driver drops BENCH_r*.json)
    bench_dir = args[0] if args else os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    rounds = load_bench_rounds(bench_dir)
    if not rounds:
        sys.stderr.write(f"no BENCH_r*.json under {bench_dir}\n")
        return 1
    t = bench_trend_summary(rounds)
    if as_json:
        sys.stdout.write(json.dumps(t) + "\n")
    else:
        sys.stdout.write(render_bench_trend(t, bench_dir))
    return 0


def main(argv: List[str]) -> int:
    """CLI entry: ``python -m lightgbm_tpu report
    {<trace.jsonl> | merge <dir|files...> | diff <a> <b> |
    costs <trace.jsonl> | bench-trend [dir]} [--json]``."""
    if argv and argv[0] == "merge":
        return merge_main(argv[1:])
    if argv and argv[0] == "diff":
        return diff_main(argv[1:])
    if argv and argv[0] == "costs":
        return costs_main(argv[1:])
    if argv and argv[0] == "bench-trend":
        return bench_trend_main(argv[1:])
    args = [a for a in argv if not a.startswith("--")]
    as_json = "--json" in argv
    if not args:
        sys.stderr.write(
            "usage: python -m lightgbm_tpu report "
            "{<trace.jsonl> | merge <dir|files...> | diff <a> <b> | "
            "costs <trace.jsonl> | bench-trend [dir]} "
            "[--json]\n"
        )
        return 2
    path = args[0]
    try:
        records = load_trace(path)
    except OSError as e:
        sys.stderr.write(f"cannot read trace {path}: {e}\n")
        return 1
    summary = summarize(records)
    if as_json:
        sys.stdout.write(json.dumps(summary) + "\n")
    else:
        sys.stdout.write(render(summary, path))
    return 0
