"""Structured run tracer — the observability core.

The reference ships compile-time TIMETAG phase timers
(serial_tree_learner.cpp:10-37, gbdt.cpp:22-63) whose only sink is a
destructor printf.  This tracer is the TPU-era replacement: nested
host-side spans, counters and gauges written as one-record-per-line JSON
(JSONL) so a failed run still leaves every record flushed before death,
plus per-iteration summary records that the bench harness and the
``python -m lightgbm_tpu report`` CLI aggregate.

Enable with ``LIGHTGBM_TPU_TRACE=/path/to/trace.jsonl`` (re-read at every
``engine.train``/``GBDT.init``) or programmatically via
``tracer.configure(path)``.  Disabled mode is near-free: ``span()``
returns a shared no-op context manager and every other entry point is a
single attribute check.

Record schema (all records carry ``ev`` and ``ts`` = time.time()):

  {"ev":"meta", "version":1, "pid":..., "argv":[...]}
  {"ev":"span", "name":..., "dur_s":..., "depth":..., "parent":..., ...attrs}
  {"ev":"counter"|"gauge", "name":..., "value":..., ...attrs}
  {"ev":"event", "name":..., ...attrs}
  {"ev":"iter", "iter":i, "wall_s":..., "phases":{name: secs},
   "compiles":n, "host_rss_mb":..., "dev_mb":..., ...fields}

Spans opened while an iteration record is open additionally accumulate
into that iteration's ``phases`` map — that is how the per-phase
histogram/split/partition breakdown lands on each ``iter`` record.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import sys
import threading
import time
from typing import Any, Dict, Optional


class _NullSpan:
    """Shared no-op context manager returned when tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def _max_bytes_from_env() -> int:
    """LIGHTGBM_TPU_TRACE_MAX_MB as a byte cap (0/unset/garbage = no
    rotation — the historical unbounded behavior)."""
    raw = os.environ.get("LIGHTGBM_TPU_TRACE_MAX_MB", "").strip()
    if not raw:
        return 0
    try:
        mb = float(raw)
    except ValueError:
        return 0
    return int(mb * 1024 * 1024) if mb > 0 else 0


def _flight_recorder():
    """Lazy accessor for the crash flight recorder (obs/flight.py) —
    imported on first enabled-mode emit, cached after."""
    global _FLIGHT
    if _FLIGHT is None:
        from . import flight

        _FLIGHT = flight.recorder
    return _FLIGHT


_FLIGHT = None


class _Span:
    __slots__ = ("_tr", "name", "attrs", "_t0")

    def __init__(self, tr: "Tracer", name: str, attrs: Dict[str, Any]):
        self._tr = tr
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._tr._stack.append(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        tr = self._tr
        stack = tr._stack
        if stack and stack[-1] is self.name:
            stack.pop()
        rec = {
            "ev": "span",
            "name": self.name,
            "dur_s": round(dur, 9),
            "depth": len(stack),
            "parent": stack[-1] if stack else None,
        }
        if self.attrs:
            rec.update(self.attrs)
        tr._emit(rec)
        agg = tr._agg.setdefault(self.name, [0.0, 0])
        agg[0] += dur
        agg[1] += 1
        if tr._iter_phases is not None:
            tr._iter_phases[self.name] = tr._iter_phases.get(self.name, 0.0) + dur
        return False


class Tracer:
    """Process-global structured tracer with a JSONL sink."""

    def __init__(self):
        self.enabled = False
        self.path: Optional[str] = None
        self._f = None
        # JSONL rotation: bytes written to the current sink file and the
        # LIGHTGBM_TPU_TRACE_MAX_MB cap (0 = unbounded).  At the cap the
        # sink rotates to <path>.1 (one generation — a bounded factory
        # run keeps at most 2x the cap on disk) and report loaders read
        # the <path>.1 + <path> pair in order.
        self._bytes = 0
        self._max_bytes = 0
        self._lock = threading.Lock()
        self._stack = []
        self._agg: Dict[str, list] = {}
        self._counters: Dict[str, float] = {}
        self._iter_phases: Optional[Dict[str, float]] = None
        self._iter_idx = None
        self._iter_t0 = 0.0
        self._iter_compiles0 = 0
        self._atexit_registered = False
        self._phases_env = None  # cached LIGHTGBM_TPU_TRACE_PHASES
        # rank/world/run_id stamped onto every record in multi-rank runs
        # so `report merge` can correlate per-rank JSONLs (empty in
        # single-process runs: records stay byte-compatible with PR 1)
        self._ident: Dict[str, Any] = {}
        # tracer-side work counter: every record actually processed
        # (emitted/mirrored) increments it.  The disabled-overhead guard
        # test pins "near-zero when off" on this staying 0 — a counter
        # of work done, not a wall-clock estimate.
        self.work_ops = 0

    # -- lifecycle -----------------------------------------------------
    def refresh_from_env(self) -> None:
        """(Re-)read LIGHTGBM_TPU_TRACE / LIGHTGBM_TPU_TRACE_PHASES; called
        at the training entry points so tests and the CLI can toggle
        tracing without importing this module early."""
        self._phases_env = os.environ.get("LIGHTGBM_TPU_TRACE_PHASES", "")
        self._ident_from_env()
        self._max_bytes = _max_bytes_from_env()
        path = os.environ.get("LIGHTGBM_TPU_TRACE", "")
        if path and path != self.path:
            self.configure(path)

    def _ident_from_env(self) -> None:
        """Pre-bootstrap identity from the launcher env (the distributed
        runtime refines it via ``set_identity`` once initialized)."""
        rank = os.environ.get("LIGHTGBM_TPU_PROCESS_ID", "").strip()
        world = os.environ.get("LIGHTGBM_TPU_NUM_PROCESSES", "").strip()
        if rank and world:
            self.set_identity(rank=int(rank), world_size=int(world))

    def set_identity(self, rank: Optional[int] = None,
                     world_size: Optional[int] = None,
                     run_id: Optional[str] = None) -> None:
        """Stamp rank/world_size/run_id onto every subsequent record.
        ``run_id`` defaults to LIGHTGBM_TPU_RUN_ID, else the coordinator
        address — both identical across ranks of one run, which is what
        ``report merge`` verifies before correlating files."""
        if rank is not None:
            self._ident["rank"] = int(rank)
        if world_size is not None:
            self._ident["world"] = int(world_size)
        if run_id is None:
            run_id = (os.environ.get("LIGHTGBM_TPU_RUN_ID", "").strip()
                      or os.environ.get("LIGHTGBM_TPU_COORDINATOR", "").strip())
        if run_id:
            self._ident["run_id"] = str(run_id)

    def configure(self, path: str) -> None:
        """Open (truncate) the JSONL sink at ``path`` and enable tracing."""
        self.close()
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "w", buffering=1)  # line buffered
        self._bytes = 0
        self._max_bytes = _max_bytes_from_env()
        self.enabled = True
        from . import compilewatch, flight

        compilewatch.install()
        # crash flight recorder: bounded ring of recent records, flushed
        # to <trace>.crash.jsonl by typed net failures / SIGUSR1
        # (obs/flight.py).  Activated ONLY here — tracing off means no
        # ring is ever allocated (the disabled-overhead guard).
        flight.recorder.activate(path)
        self._emit({
            "ev": "meta",
            "version": 1,
            "pid": os.getpid(),
            "argv": sys.argv,
        })
        if not self._atexit_registered:
            atexit.register(self.close)
            self._atexit_registered = True

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.flush()
                self._f.close()
            except Exception:  # pragma: no cover - interpreter teardown
                pass
            try:
                from . import flight

                flight.recorder.deactivate()
            except Exception:  # pragma: no cover - interpreter teardown
                pass
        self._f = None
        self.enabled = False

    def phases_enabled(self, default: bool = False) -> bool:
        """Per-phase (defused) tracing mode: '1' forces on, '0' forces
        off, unset/'auto' -> caller's default (the partitioned trainer
        defaults to ON in interpret mode and OFF on a real TPU, where
        defusing the chunk program changes the very timings being
        measured)."""
        if self._phases_env is None:
            self._phases_env = os.environ.get("LIGHTGBM_TPU_TRACE_PHASES", "")
        if self._phases_env == "1":
            return True
        if self._phases_env == "0":
            return False
        return default

    # -- emission ------------------------------------------------------
    def _emit(self, rec: Dict[str, Any]) -> None:
        if self._ident:
            for k, v in self._ident.items():
                rec.setdefault(k, v)
        rec.setdefault("ts", round(time.time(), 6))
        line = json.dumps(rec, default=str)
        self.work_ops += 1
        _flight_recorder().record(rec)
        with self._lock:
            if self._f is not None:
                self._f.write(line + "\n")
                self._bytes += len(line) + 1
                if self._max_bytes and self._bytes >= self._max_bytes:
                    self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Size-capped sink rotation (caller holds ``_lock``): the
        current file becomes ``<path>.1`` (clobbering any previous
        generation) and a fresh sink opens at ``path`` with a new meta
        record so the rotated pair is self-describing."""
        try:
            self._f.flush()
            self._f.close()
            os.replace(self.path, self.path + ".1")
        except OSError:  # pragma: no cover - exotic fs; keep tracing
            pass
        self._f = open(self.path, "w", buffering=1)
        self._bytes = 0
        meta = {"ev": "meta", "version": 1, "pid": os.getpid(),
                "rotated": True, "ts": round(time.time(), 6)}
        meta.update(self._ident)
        line = json.dumps(meta)
        self._f.write(line + "\n")
        self._bytes += len(line) + 1

    def span(self, name: str, **attrs):
        """Timed nested span context manager (no-op singleton when
        disabled — near-zero overhead on hot paths)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, attrs)

    def counter(self, name: str, value: float = 1.0, **attrs) -> None:
        if not self.enabled:
            return
        self._counters[name] = self._counters.get(name, 0.0) + value
        rec = {"ev": "counter", "name": name, "value": value}
        rec.update(attrs)
        self._emit(rec)
        from . import metrics

        metrics.registry.trace_counter(name, value)

    def gauge(self, name: str, value: float, **attrs) -> None:
        if not self.enabled:
            return
        rec = {"ev": "gauge", "name": name, "value": value}
        rec.update(attrs)
        self._emit(rec)
        from . import metrics

        metrics.registry.trace_gauge(name, value)

    def event(self, name: str, **attrs) -> None:
        if not self.enabled:
            return
        rec = {"ev": "event", "name": name}
        rec.update(attrs)
        self._emit(rec)

    # -- per-iteration records -----------------------------------------
    @contextlib.contextmanager
    def iteration(self, it: int, **fields):
        """Open a per-iteration record; spans entered inside accumulate
        into its ``phases`` map.  Yields a mutable dict callers can add
        fields to (leaves, bagged_rows, ...).  On close the record gains
        wall time, compile-count delta and memory gauges."""
        if not self.enabled:
            yield None
            return
        from . import compilewatch, memory

        prev_phases = self._iter_phases
        self._iter_phases = {}
        self._iter_idx = it
        c0 = compilewatch.total_compiles()
        t0 = time.perf_counter()
        rec: Dict[str, Any] = dict(fields)
        try:
            yield rec
        finally:
            wall = time.perf_counter() - t0
            out = {
                "ev": "iter",
                "iter": int(it),
                "wall_s": round(wall, 6),
                "phases": {k: round(v, 6) for k, v in self._iter_phases.items()},
                "compiles": compilewatch.total_compiles() - c0,
            }
            out.update(memory.memory_gauges())
            out.update(rec)
            self._emit(out)
            self._iter_phases = prev_phases
            self._iter_idx = None

    def emit_iter(self, it: int, wall_s: float, phases: Dict[str, float],
                  **fields) -> None:
        """Directly write an iteration record (the fused chunk path emits
        amortized per-iteration records after the chunk completes)."""
        if not self.enabled:
            return
        from . import memory

        rec = {
            "ev": "iter",
            "iter": int(it),
            "wall_s": round(wall_s, 6),
            "phases": {k: round(v, 6) for k, v in phases.items()},
        }
        rec.update(memory.memory_gauges())
        rec.update(fields)
        self._emit(rec)

    # -- aggregates ----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Host-side aggregate view (phase totals/counts, counters) —
        what bench.py embeds into its JSON output."""
        return {
            "spans": {
                name: {"total_s": round(t, 6), "count": c,
                       "mean_ms": round(1e3 * t / max(c, 1), 3)}
                for name, (t, c) in sorted(self._agg.items())
            },
            "counters": dict(self._counters),
        }

    def reset_aggregates(self) -> None:
        self._agg.clear()
        self._counters.clear()


tracer = Tracer()


def fence(x):
    """``jax.block_until_ready`` gate used at phase boundaries: a no-op
    unless tracing is enabled, so the async dispatch pipeline is never
    serialized in production runs.  Returns ``x``."""
    if tracer.enabled and x is not None:
        import jax

        jax.block_until_ready(x)
    return x
