"""Dependency-free Prometheus text-format metrics registry.

The PR-2 serving layer kept its operational counters in per-batcher
dicts behind a poll-once ``/stats`` JSON — fine for a human, useless for
a fleet: Prometheus cannot scrape it, counters reset per batcher, and
nothing exposes histograms.  This module is the scrape surface:

- :class:`MetricsRegistry` holds **counters** (monotone), **gauges**
  (sampled) and **fixed-bucket histograms** (cumulative ``le`` buckets,
  ``_sum``/``_count``), all thread-safe and allocation-light enough to
  sit on the serving request path.
- Metrics may be **fn-backed**: the value is read at render time from a
  callback (uptime, readiness, compile accounting) so scraping costs
  nothing between scrapes and NEVER touches jax — a ``GET /metrics``
  can not trigger an XLA compile (pinned by tests/test_metrics.py).
- ``render()`` emits the Prometheus exposition text format
  (``# HELP`` / ``# TYPE`` lines, cumulative histogram buckets ending
  at ``le="+Inf"``), served by ``GET /metrics`` on the serve front end
  and dumpable at end-of-train via ``LIGHTGBM_TPU_METRICS=path``.
- The run tracer (obs/trace.py) mirrors every enabled-mode
  ``tracer.counter``/``tracer.gauge`` here under the mechanical mapping
  ``name.with.dots`` -> ``lightgbm_tpu_name_with_dots[_total]``, so a
  training run's net/ckpt/ingest counters land in the same dump without
  a second instrumentation pass.  With tracing off the mirror is never
  called (the tracer entry points return before reaching it).

Every metric name in this module is part of the observability interface
and must appear in the docs/OBSERVABILITY.md name registry — a tier-1
lint test walks the source and fails on undocumented names.
"""

from __future__ import annotations

import re
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

PREFIX = "lightgbm_tpu_"

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize(name: str) -> str:
    """Tracer-name -> Prometheus-name fragment (dots become underscores,
    anything else illegal collapses to '_')."""
    return _SANITIZE.sub("_", name)


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render without the trailing
    '.0' (counters are usually whole), floats via repr (full
    round-trip precision)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Counter:
    """Monotone counter.  ``fn``-backed counters read their value at
    render time (the underlying source must itself be monotone)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.help = help
        self.fn = fn
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, value: float = 1.0) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._value += value

    def value(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:
                return 0.0
        return self._value

    def samples(self) -> List[str]:
        return [f"{self.name} {_fmt(self.value())}"]


class Gauge:
    """Sampled value; ``fn``-backed gauges evaluate at render time."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.help = help
        self.fn = fn
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self._value += value

    def value(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:
                return 0.0
        return self._value

    def samples(self) -> List[str]:
        return [f"{self.name} {_fmt(self.value())}"]


# default latency ladder (seconds): sub-ms serving hits through
# multi-second stragglers
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
# power-of-two row ladder matching the serving bucket ladder
BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                 512.0, 1024.0, 2048.0, 4096.0)


class Histogram:
    """Fixed-bucket histogram: per-bucket counts are kept exclusive and
    rendered cumulative with a final ``le="+Inf"`` bucket, plus
    ``_sum`` and ``_count`` series (the Prometheus contract
    ``bucket[+Inf] == count``)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self._counts = [0] * (len(self.buckets) + 1)  # last = overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        i = len(self.buckets)
        for j, b in enumerate(self.buckets):
            if v <= b:
                i = j
                break
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def value(self) -> float:  # symmetry with counter/gauge (snapshot())
        return float(self._count)

    def quantile(self, q: float) -> float:
        """Smallest bucket upper bound covering fraction ``q`` of the
        observations (0.0 when empty).  Bucket-resolution only — what
        an SLO verdict needs, not a billing meter."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total <= 0:
            return 0.0
        target = float(q) * total
        acc = 0
        for b, c in zip(self.buckets, counts):
            acc += c
            if acc >= target:
                return float(b)
        return float(self.buckets[-1])

    def samples(self) -> List[str]:
        with self._lock:
            counts = list(self._counts)
            total = self._count
            s = self._sum
        out = []
        acc = 0
        for b, c in zip(self.buckets, counts):
            acc += c
            out.append(f'{self.name}_bucket{{le="{b:g}"}} {acc}')
        out.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        out.append(f"{self.name}_sum {_fmt(s)}")
        out.append(f"{self.name}_count {total}")
        return out


class RollingQuantile:
    """Exact quantiles over a sliding window of the last ``window``
    observations.  Unlike :class:`Histogram` (cumulative, bucket
    resolution) this *adapts*: the fleet proxy derives its hedge delay
    from the p95 of recent attempt latencies, so the trigger tracks the
    fleet's current speed instead of its lifetime average.  Not a
    Prometheus metric — a control-loop input."""

    def __init__(self, window: int = 512):
        self._window = max(1, int(window))
        self._buf: deque = deque(maxlen=self._window)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._buf.append(float(value))

    def count(self) -> int:
        with self._lock:
            return len(self._buf)

    def quantile(self, q: float) -> float:
        """Exact order statistic over the window (0.0 when empty)."""
        with self._lock:
            vals = sorted(self._buf)
        if not vals:
            return 0.0
        i = min(len(vals) - 1, max(0, int(float(q) * len(vals))))
        return vals[i]


class LabeledFamily:
    """One metric family split by a single label — per-model-version
    serving metrics (``requests{model_version="3"}``) without an
    unbounded cardinality risk: children are created per label value and
    ``prune()``'d back to the versions actually loaded after every swap.
    Child samples are re-emitted with the label pair injected, merging
    with any labels the child already carries (histogram ``le``)."""

    def __init__(self, name: str, help: str = "", child_cls=Counter,
                 label: str = "model_version", **kw):
        self.name = name
        self.help = help
        self.cls = child_cls
        self.kind = child_cls.kind
        self.label = label
        self._kw = kw
        self._children: Dict[str, object] = {}
        self._lock = threading.Lock()

    def labels(self, value) -> object:
        key = str(value)
        with self._lock:
            c = self._children.get(key)
            if c is None:
                c = self.cls(self.name, self.help, **self._kw)
                self._children[key] = c
            return c

    def children(self) -> Dict[str, object]:
        with self._lock:
            return dict(self._children)

    def prune(self, keep) -> None:
        """Drop children whose label value is not in ``keep`` — bounds
        scrape cardinality to the versions currently loaded."""
        keep = {str(k) for k in keep}
        with self._lock:
            for k in list(self._children):
                if k not in keep:
                    del self._children[k]

    def value(self) -> float:
        return sum(c.value() for c in self.children().values())

    def samples(self) -> List[str]:
        out: List[str] = []
        for key, c in sorted(self.children().items()):
            pair = f'{self.label}="{key}"'
            for s in c.samples():
                metric, val = s.rsplit(None, 1)
                if "{" in metric:
                    head, rest = metric.split("{", 1)
                    out.append(f"{head}{{{pair},{rest} {val}")
                else:
                    out.append(f"{metric}{{{pair}}} {val}")
        return out


class MetricsRegistry:
    """Process-global named-metric store.  ``counter``/``gauge``/
    ``histogram`` are get-or-create (idempotent by name); re-registering
    an fn-backed metric replaces the callback (tests and the serve
    layer construct servers repeatedly in one process — latest wins)."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help: str, **kw):
        if not _NAME_OK.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kw)
                self._metrics[name] = m
                return m
            if not isinstance(m, cls):
                raise TypeError(
                    f"metric {name} already registered as {m.kind}"
                )
            if kw.get("fn") is not None:
                m.fn = kw["fn"]
            if help and not m.help:
                m.help = help
            return m

    def counter(self, name: str, help: str = "",
                fn: Optional[Callable[[], float]] = None) -> Counter:
        return self._get_or_create(Counter, name, help, fn=fn)

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, fn=fn)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def labeled_counter(self, name: str, help: str = "",
                        label: str = "model_version") -> LabeledFamily:
        return self._get_or_create(LabeledFamily, name, help,
                                   child_cls=Counter, label=label)

    def labeled_histogram(self, name: str, help: str = "",
                          label: str = "model_version",
                          buckets: Sequence[float] = LATENCY_BUCKETS,
                          ) -> LabeledFamily:
        return self._get_or_create(LabeledFamily, name, help,
                                   child_cls=Histogram, label=label,
                                   buckets=buckets)

    # -- tracer mirror -------------------------------------------------
    def _mirror_target(self, n: str):
        """Get-or-create the mirror metric ``n`` unless that name is
        already EXPLICITLY instrumented at the source (the serve layer
        both updates its registry metrics directly and traces the same
        signal — mirroring would double count).  Mirror-created metrics
        are tagged so repeat mirrors keep flowing to them."""
        with self._lock:
            m = self._metrics.get(n)
        if m is not None and not getattr(m, "mirrored", False):
            return None
        return m

    def trace_counter(self, name: str, value: float) -> None:
        """Mirror of an enabled-mode ``tracer.counter``: dotted trace
        names land as ``lightgbm_tpu_<sanitized>_total``."""
        n = PREFIX + sanitize(name)
        if not n.endswith("_total"):
            n += "_total"
        m = self._mirror_target(n)
        if m is None:
            with self._lock:
                if n in self._metrics:
                    return
            m = self.counter(n, help=f"mirror of trace counter {name}")
            m.mirrored = True
        m.inc(value)

    def trace_gauge(self, name: str, value: float) -> None:
        n = PREFIX + sanitize(name)
        m = self._mirror_target(n)
        if m is None:
            with self._lock:
                if n in self._metrics:
                    return
            m = self.gauge(n, help=f"mirror of trace gauge {name}")
            m.mirrored = True
        m.set(value)

    # -- output --------------------------------------------------------
    def render(self) -> str:
        """Prometheus exposition text format (content type
        ``text/plain; version=0.0.4``).  Never imports or touches jax:
        fn-backed metrics must read plain host state only."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in sorted(metrics, key=lambda m: m.name):
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.samples())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> Dict[str, float]:
        """{name: scalar value} view (histograms report their count) —
        what bench.py embeds and tests assert against."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: m.value() for m in metrics}

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.render())

    def _reset_for_tests(self) -> None:
        """Zero every stored value IN PLACE — modules hold references to
        their metric objects, so clearing the dict would orphan them.
        fn-backed metrics read external monotone state and are left
        alone (tests compare deltas on those)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, LabeledFamily):
                with m._lock:
                    m._children.clear()
            elif isinstance(m, Histogram):
                with m._lock:
                    m._counts = [0] * (len(m.buckets) + 1)
                    m._sum = 0.0
                    m._count = 0
            elif m.fn is None:
                with m._lock:
                    m._value = 0.0


registry = MetricsRegistry()


def _compile_stat(key: str) -> Callable[[], float]:
    def read() -> float:
        from . import compilewatch

        return float(compilewatch.snapshot()[key])

    return read


def _watched_stat(watch: str, key: str) -> Callable[[], float]:
    def read() -> float:
        from . import compilewatch

        return float(compilewatch.snapshot()["watched"].get(watch, {})
                     .get(key, 0))

    return read


def _install_default_collectors(reg: MetricsRegistry) -> None:
    """Compile accounting is useful in every process (train or serve),
    costs nothing until rendered, and reads plain counters — register
    the fn-backed metrics once at import."""
    reg.counter("lightgbm_tpu_xla_compiles_total",
                "XLA backend compilations observed by obs/compilewatch",
                fn=_compile_stat("backend_compiles"))
    reg.counter("lightgbm_tpu_xla_compile_seconds_total",
                "cumulative XLA backend compile seconds",
                fn=_compile_stat("backend_compile_secs"))
    reg.counter("lightgbm_tpu_serve_predict_compiles_total",
                "compiles of the watched serve.predict_raw entry point",
                fn=_watched_stat("serve.predict_raw", "compiles"))
    reg.counter("lightgbm_tpu_serve_predict_retraces_total",
                "unexpected retraces flagged on serve.predict_raw",
                fn=_watched_stat("serve.predict_raw", "retraces"))


_install_default_collectors(registry)


def parse_text_format(text: str) -> Dict[str, Dict]:
    """Minimal exposition-format parser for tests and the report CLI:
    returns {metric_family: {"type": ..., "samples": {sample_key: value}}}
    where sample_key includes any label suffix (e.g. 'name_bucket{le="1"}').
    Raises ValueError on malformed lines — the format-validity test
    feeds every scrape through this."""
    out: Dict[str, Dict] = {}
    current: Optional[str] = None
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) != 4 or parts[3] not in ("counter", "gauge",
                                                   "histogram", "summary",
                                                   "untyped"):
                raise ValueError(f"line {ln}: malformed TYPE line {line!r}")
            current = parts[2]
            out[current] = {"type": parts[3], "samples": {}}
            continue
        if line.startswith("#"):
            raise ValueError(f"line {ln}: unknown comment {line!r}")
        try:
            key, val = line.rsplit(None, 1)
            fval = float(val)
        except ValueError:
            raise ValueError(f"line {ln}: malformed sample {line!r}")
        base = key.split("{")[0]
        fam = None
        for suffix in ("_bucket", "_sum", "_count", ""):
            cand = base[: len(base) - len(suffix)] if suffix else base
            if suffix and not base.endswith(suffix):
                continue
            if cand in out:
                fam = cand
                break
        if fam is None:
            raise ValueError(f"line {ln}: sample {key!r} precedes its TYPE line")
        if not _NAME_OK.match(base):
            raise ValueError(f"line {ln}: invalid sample name {base!r}")
        out[fam]["samples"][key] = fval
    return out
