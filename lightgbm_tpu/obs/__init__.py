"""Observability layer: structured run tracing, compile/retrace
accounting, memory gauges and trace reports.

Import surface (kept tiny — hot paths touch only ``tracer``/``fence``):

  from lightgbm_tpu.obs import tracer, fence
  tracer.refresh_from_env()           # LIGHTGBM_TPU_TRACE=trace.jsonl
  with tracer.span("histogram"): ...
  with tracer.iteration(i) as rec: rec["leaves"] = 31

Submodules: ``trace`` (spans/counters/gauges/iteration records, JSONL
sink with LIGHTGBM_TPU_TRACE_MAX_MB rotation), ``compilewatch``
(jax.monitoring compile counter + JitWatch retrace detector + the
first-compile HLO cost capture), ``costmodel`` (per-program flops/bytes
inventory, peak-spec roofline, per-phase efficiency attribution),
``memory`` (host/device gauges), ``report`` (aggregation + the
``python -m lightgbm_tpu report`` CLI, incl. the cross-rank ``merge``,
audit ``diff``, ``costs`` and ``bench-trend`` subcommands), ``metrics``
(Prometheus text-format registry behind ``GET /metrics``), ``audit``
(LIGHTGBM_TPU_AUDIT split-decision trail), ``flight`` (crash flight
recorder dumping to ``<trace>.crash.jsonl``).
"""

from .trace import Tracer, fence, tracer  # noqa: F401
from .compilewatch import JitWatch  # noqa: F401

__all__ = ["Tracer", "tracer", "fence", "JitWatch"]
