"""Compiled-program cost accounting — HLO roofline model.

Five rounds of perf work (PRs 6, 8, 12-15) answered "faster than last
time?"; this module answers "how far from the machine?".  Every
``JitWatch``-wrapped program records, at first compile per argument
signature, XLA's HLO cost analysis (flops, bytes accessed,
transcendentals) and — when a re-compile is cheap enough to afford —
the compiled memory analysis (peak temp / argument / output bytes).
Each capture lands as a ``jax_cost`` trace record AND in a
process-global program inventory, so both the offline report
(``python -m lightgbm_tpu report costs <trace>``) and the in-process
bench harness can join program costs against measured phase spans.

The join produces, per phase, an **efficiency %**: the roofline
lower-bound time (``max(flops/peak_flops, bytes/peak_bw)`` per call,
times the measured call count) divided by the measured wall.  The
"next kernel target" is the phase with the most reclaimable wall —
``measured - roofline`` — which is exactly "lowest efficiency weighted
by share of wall".

Peak specs are nominal public per-chip numbers (bf16 MXU flops + HBM
bandwidth); override or extend with ``LIGHTGBM_TPU_PEAK_SPECS`` as a
JSON object, e.g.::

  LIGHTGBM_TPU_PEAK_SPECS='{"cpu": {"flops_per_s": 1e11,
                                    "hbm_bytes_per_s": 3e10}}'

Spec keys are matched case-insensitively as substrings of the JAX
``device_kind`` (longest key wins), so "tpu v5 lite" matches the
device kind ``TPU v5 lite``.  The CPU fallback is deliberately a rough
host-class number — on the dead tunnel the point is *relative* phase
ranking, not absolute truth; absolute truth arrives with the device.
"""

from __future__ import annotations

import json
import math
import os
import threading
from typing import Any, Dict, List, Optional

from ..utils.log import Log

# Nominal per-chip peaks: bf16 MXU flops + HBM bandwidth (public specs;
# v4 275 Tflops / 1228 GB/s, v5e ("v5 lite") 197 Tflops / 819 GB/s,
# v5p 459 Tflops / 2765 GB/s).  The cpu row is a nominal host-class
# vector unit + DRAM figure, present so the dead-tunnel CPU runs still
# produce a ranking.
DEFAULT_PEAK_SPECS: Dict[str, Dict[str, float]] = {
    "tpu v4": {"flops_per_s": 275e12, "hbm_bytes_per_s": 1228e9},
    "tpu v5 lite": {"flops_per_s": 197e12, "hbm_bytes_per_s": 819e9},
    "tpu v5e": {"flops_per_s": 197e12, "hbm_bytes_per_s": 819e9},
    "tpu v5p": {"flops_per_s": 459e12, "hbm_bytes_per_s": 2765e9},
    "cpu": {"flops_per_s": 1e11, "hbm_bytes_per_s": 3e10},
}

# at most this many per-signature cost records are kept per program —
# the serving bucket ladder can legitimately compile dozens of shapes
_MAX_SIGS_PER_PROGRAM = 8

_lock = threading.Lock()
# program name -> {"phase": str|None, "backend": str, "records": [dict]}
_inventory: Dict[str, Dict[str, Any]] = {}
# (program, signature) pairs already captured this process — JitWatch
# instances are rebuilt per trainer, so without this a suite that trains
# many boosters re-pays the lower()/AOT-compile capture for the same
# program+shapes on every run
_captured: set = set()


def reset() -> None:
    """Clear the process-global program inventory (tests)."""
    with _lock:
        _inventory.clear()
        _captured.clear()


def enabled() -> bool:
    """Cost capture kill switch: LIGHTGBM_TPU_COSTMODEL=0 disables the
    lower/cost-analysis pass at first compile (it re-traces the program
    once, which a latency-critical caller may not want to pay)."""
    return os.environ.get("LIGHTGBM_TPU_COSTMODEL", "1") != "0"


def deep_budget_s() -> float:
    """Compile-time budget (seconds) under which the capture also runs
    ``lowered.compile()`` for the post-optimization memory analysis.
    The AOT compile is NOT shared with the dispatch cache, so a program
    that took 30 s to compile would take ~30 s again — the budget keeps
    the deep pass to programs whose observed backend compile was cheap
    (default 2 s)."""
    try:
        return float(os.environ.get("LIGHTGBM_TPU_COSTMODEL_DEEP_BUDGET",
                                    "2.0"))
    except ValueError:
        return 2.0


# ----------------------------------------------------------------------
# peak specs + roofline arithmetic
# ----------------------------------------------------------------------
def peak_specs() -> Dict[str, Dict[str, float]]:
    """Default spec table merged with the LIGHTGBM_TPU_PEAK_SPECS JSON
    override (override wins per key; malformed JSON warns and is
    ignored)."""
    specs = {k: dict(v) for k, v in DEFAULT_PEAK_SPECS.items()}
    raw = os.environ.get("LIGHTGBM_TPU_PEAK_SPECS", "").strip()
    if raw:
        try:
            user = json.loads(raw)
            if not isinstance(user, dict):
                raise ValueError("not a JSON object")
            for k, v in user.items():
                row = specs.setdefault(str(k).lower(), {})
                row.update({kk: float(vv) for kk, vv in v.items()})
                row["source"] = "env"
        except (ValueError, TypeError, AttributeError) as e:
            Log.warning("ignoring malformed LIGHTGBM_TPU_PEAK_SPECS: %s", e)
    return specs


def resolve_peak_spec(device_kind: Optional[str] = None) -> Dict[str, Any]:
    """Pick the spec row for ``device_kind`` (default: the first JAX
    device's kind).  Keys match case-insensitively as substrings of the
    kind, longest key first; no match falls back to the ``cpu`` row."""
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:  # pragma: no cover - no backend at all
            device_kind = "cpu"
    kind = str(device_kind).lower()
    specs = peak_specs()
    match = None
    for key in sorted(specs, key=len, reverse=True):
        if key in kind:
            match = key
            break
    if match is None:
        match = "cpu"
    row = specs.get(match, DEFAULT_PEAK_SPECS["cpu"])
    return {
        "key": match,
        "device_kind": str(device_kind),
        "flops_per_s": float(row["flops_per_s"]),
        "hbm_bytes_per_s": float(row["hbm_bytes_per_s"]),
        "source": row.get("source", "default"),
    }


def roofline(flops: float, bytes_accessed: float, transcendentals: float,
             spec: Dict[str, Any]) -> Dict[str, Any]:
    """Roofline estimate for one program call: arithmetic intensity
    (flop/byte), compute- vs memory-bound verdict against the spec's
    ridge point, and the lower-bound seconds per call.  Transcendentals
    are charged as one flop each (XLA counts them separately)."""
    pf = float(spec["flops_per_s"])
    pb = float(spec["hbm_bytes_per_s"])
    work = float(flops) + float(transcendentals)
    compute_s = work / pf if pf > 0 else 0.0
    memory_s = float(bytes_accessed) / pb if pb > 0 else 0.0
    ai = (work / float(bytes_accessed)) if bytes_accessed > 0 else math.inf
    return {
        "ai": round(ai, 4) if math.isfinite(ai) else None,
        "ridge_ai": round(pf / pb, 2) if pb > 0 else None,
        "bound": "compute" if compute_s >= memory_s else "memory",
        "lb_s": max(compute_s, memory_s),
    }


# ----------------------------------------------------------------------
# capture (called from JitWatch at first compile per signature)
# ----------------------------------------------------------------------
def _nbytes(leaves) -> int:
    total = 0
    for l in leaves:
        shape = getattr(l, "shape", None)
        dtype = getattr(l, "dtype", None)
        if shape is None or dtype is None:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        total += n * int(getattr(dtype, "itemsize", 4))
    return total


def _cost_dict(cost) -> Dict[str, float]:
    """Normalize a cost_analysis() result: Lowered returns a flat dict,
    Compiled returns a one-element list of dicts."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    return cost


def capture(watch, args, kwargs, compile_secs: Optional[float],
            sig=None) -> Optional[dict]:
    """Scrape HLO cost/memory analysis for a freshly-compiled signature
    of ``watch`` (a JitWatch) and record it: ``jax_cost`` trace event +
    process-global inventory row.  Returns the record, or None when the
    capture is disabled, the callable has no AOT surface, or the work
    would be thrown away (program+signature already captured this
    process, or the program's inventory is full) — the skip check runs
    BEFORE the lower() so a suite that trains many boosters does not
    re-pay the re-trace per booster."""
    if not enabled():
        return None
    with _lock:
        if sig is not None and (watch.name, sig) in _captured:
            return None
        entry = _inventory.get(watch.name)
        if entry is not None and len(entry["records"]) >= _MAX_SIGS_PER_PROGRAM:
            return None
    import jax

    fn = watch._fn
    lower = getattr(fn, "lower", None)
    if lower is None:
        return None
    lowered = lower(*args, **kwargs)
    cost = _cost_dict(lowered.cost_analysis())
    rec: Dict[str, Any] = {
        "program": watch.name,
        "phase": watch.phase,
        "backend": str(jax.devices()[0].device_kind),
        "level": "lowered",
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "transcendentals": float(cost.get("transcendentals", 0.0)),
        "arg_bytes": _nbytes(jax.tree_util.tree_leaves((args, kwargs))),
        "out_bytes": _nbytes(jax.tree_util.tree_leaves(lowered.out_info)),
        "compile_secs": round(float(compile_secs or 0.0), 4),
    }
    # deep pass: a real AOT compile (NOT shared with the dispatch cache)
    # for the post-optimization cost + memory analysis — only when the
    # observed backend compile was cheap enough to pay twice
    if compile_secs is not None and compile_secs <= deep_budget_s():
        try:
            compiled = lowered.compile()
            dcost = _cost_dict(compiled.cost_analysis())
            if dcost:
                rec["flops"] = float(dcost.get("flops", rec["flops"]))
                rec["bytes_accessed"] = float(
                    dcost.get("bytes accessed", rec["bytes_accessed"]))
                rec["transcendentals"] = float(
                    dcost.get("transcendentals", rec["transcendentals"]))
            mem = compiled.memory_analysis()
            if mem is not None:
                rec["temp_bytes"] = int(
                    getattr(mem, "temp_size_in_bytes", 0))
                rec["arg_bytes"] = int(
                    getattr(mem, "argument_size_in_bytes", rec["arg_bytes"]))
                rec["out_bytes"] = int(
                    getattr(mem, "output_size_in_bytes", rec["out_bytes"]))
                rec["code_bytes"] = int(
                    getattr(mem, "generated_code_size_in_bytes", 0))
            rec["level"] = "compiled"
        except Exception as e:  # pragma: no cover - backend-specific AOT gaps
            Log.warning("deep cost pass failed for %s: %s", watch.name, e)
    _record(rec)
    if sig is not None:
        with _lock:
            _captured.add((watch.name, sig))
    return rec


def _record(rec: Dict[str, Any]) -> None:
    with _lock:
        entry = _inventory.setdefault(rec["program"], {
            "phase": rec.get("phase"),
            "backend": rec.get("backend"),
            "records": [],
        })
        if len(entry["records"]) < _MAX_SIGS_PER_PROGRAM:
            entry["records"].append(dict(rec))
    from .trace import tracer

    tracer.event("jax_cost", **{k: v for k, v in rec.items()})


def inventory() -> Dict[str, Dict[str, Any]]:
    """Snapshot of the process-global program inventory."""
    with _lock:
        return {k: {"phase": v["phase"], "backend": v["backend"],
                    "records": [dict(r) for r in v["records"]]}
                for k, v in _inventory.items()}


# ----------------------------------------------------------------------
# join: program costs x measured phase spans -> efficiency table
# ----------------------------------------------------------------------
def programs_from_trace(records: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Rebuild the program inventory from ``jax_cost`` records of a
    JSONL trace stream (the offline mirror of :func:`inventory`)."""
    by: Dict[str, Dict[str, Any]] = {}
    for r in records:
        if r.get("ev") != "event" or r.get("name") != "jax_cost":
            continue
        entry = by.setdefault(str(r.get("program")), {
            "phase": r.get("phase"),
            "backend": r.get("backend"),
            "records": [],
        })
        if len(entry["records"]) < _MAX_SIGS_PER_PROGRAM:
            entry["records"].append(r)
    return by


def phase_stats_from_trace(records: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """{span name: {"total_s", "count"}} over a trace stream."""
    out: Dict[str, Dict[str, Any]] = {}
    for r in records:
        if r.get("ev") != "span":
            continue
        agg = out.setdefault(str(r.get("name", "?")),
                             {"total_s": 0.0, "count": 0})
        agg["total_s"] += float(r.get("dur_s", 0.0))
        agg["count"] += 1
    return out


def program_stats(entry: Dict[str, Any], spec: Dict[str, Any]) -> Dict[str, Any]:
    """Per-program cost summary: means across recorded signatures (the
    bucket-ladder programs compile many shapes; the mean is the honest
    single number when per-signature call counts are unknown) plus the
    roofline verdict on those means."""
    recs = entry.get("records") or []
    n = max(len(recs), 1)
    flops = sum(float(r.get("flops", 0.0)) for r in recs) / n
    nbytes = sum(float(r.get("bytes_accessed", 0.0)) for r in recs) / n
    trans = sum(float(r.get("transcendentals", 0.0)) for r in recs) / n
    rl = roofline(flops, nbytes, trans, spec)
    out = {
        "phase": entry.get("phase"),
        "signatures": len(recs),
        "flops_per_call": flops,
        "bytes_per_call": nbytes,
        "transcendentals_per_call": trans,
        "ai": rl["ai"],
        "bound": rl["bound"],
        "roofline_s_per_call": rl["lb_s"],
        "level": (recs[-1].get("level") if recs else None),
    }
    temps = [int(r["temp_bytes"]) for r in recs if r.get("temp_bytes")]
    if temps:
        out["peak_temp_bytes"] = max(temps)
    return out


def efficiency_table(phase_stats: Dict[str, Dict[str, Any]],
                     programs: Dict[str, Dict[str, Any]],
                     spec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Join program rooflines against measured phase spans.

    When several programs map to one phase (traced-mode ``update`` and
    the standalone ``ops.build_histogram`` both tag ``histogram``), the
    one with the largest per-call roofline represents the phase — the
    others are variants of the same work, and one span = one call of
    the representative.  Rows sort by measured wall, descending."""
    by_phase: Dict[str, List[str]] = {}
    for name, entry in programs.items():
        ph = entry.get("phase")
        if ph:
            by_phase.setdefault(str(ph), []).append(name)
    rows: List[Dict[str, Any]] = []
    total_measured = 0.0
    for ph, names in by_phase.items():
        meas = phase_stats.get(ph)
        if not meas or meas.get("count", 0) <= 0:
            continue
        stats = {n: program_stats(programs[n], spec) for n in names}
        rep = max(names, key=lambda n: stats[n]["roofline_s_per_call"])
        st = stats[rep]
        measured = float(meas["total_s"])
        count = int(meas["count"])
        roof = st["roofline_s_per_call"] * count
        eff = 100.0 * roof / measured if measured > 0 else None
        rows.append({
            "phase": ph,
            "program": rep,
            "calls": count,
            "measured_s": round(measured, 6),
            "roofline_s": round(roof, 6),
            "efficiency_pct": round(eff, 2) if eff is not None else None,
            "headroom_s": round(max(measured - roof, 0.0), 6),
            "ai": st["ai"],
            "bound": st["bound"],
        })
        total_measured += measured
    for row in rows:
        row["share_pct"] = round(
            100.0 * row["measured_s"] / total_measured, 1
        ) if total_measured > 0 else None
    rows.sort(key=lambda r: -r["measured_s"])
    return rows


def next_target(rows: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The machine-picked optimization target: the phase with the most
    reclaimable wall (measured - roofline) — equivalently, the lowest
    efficiency weighted by share of wall."""
    candidates = [r for r in rows if r.get("headroom_s", 0.0) > 0.0]
    if not candidates:
        return None
    return max(candidates, key=lambda r: r["headroom_s"])


def next_target_line(rows: List[Dict[str, Any]]) -> str:
    t = next_target(rows)
    if t is None:
        return ""
    eff = t.get("efficiency_pct")
    eff_txt = f"{eff:.1f}%" if eff is not None else "n/a"
    return (f"next kernel target: {t['phase']} ({t['program']}) — "
            f"{eff_txt} of roofline at {t['share_pct']:.1f}% of phase "
            f"wall, headroom {t['headroom_s']:.3f} s")


def costs_summary(records: List[Dict[str, Any]],
                  spec: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Full cost-model summary from a loaded trace stream: resolved
    peak spec, per-program inventory stats, the per-phase efficiency
    table and the next-target pick."""
    programs = programs_from_trace(records)
    if spec is None:
        backend = next((e.get("backend") for e in programs.values()
                        if e.get("backend")), None)
        spec = resolve_peak_spec(backend)
    table = efficiency_table(phase_stats_from_trace(records), programs, spec)
    return {
        "peak_spec": spec,
        "n_programs": len(programs),
        "n_signatures": sum(len(e["records"]) for e in programs.values()),
        "programs": {n: program_stats(e, spec)
                     for n, e in sorted(programs.items())},
        "table": table,
        "next_target": next_target(table),
        "next_target_line": next_target_line(table),
    }


def process_summary(spec: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Same summary from the LIVE process state: the global inventory
    joined against the tracer's span aggregates — what bench.py embeds
    as its ``cost_model`` section."""
    from .trace import tracer

    programs = inventory()
    if spec is None:
        backend = next((e.get("backend") for e in programs.values()
                        if e.get("backend")), None)
        spec = resolve_peak_spec(backend)
    snap = tracer.snapshot()["spans"]
    phase_stats = {name: {"total_s": float(v["total_s"]),
                          "count": int(v["count"])}
                   for name, v in snap.items()}
    table = efficiency_table(phase_stats, programs, spec)
    return {
        "peak_spec": spec,
        "n_programs": len(programs),
        "n_signatures": sum(len(e["records"]) for e in programs.values()),
        "programs": {n: program_stats(e, spec)
                     for n, e in sorted(programs.items())},
        "table": table,
        "next_target": next_target(table),
        "next_target_line": next_target_line(table),
    }


# ----------------------------------------------------------------------
# rendering
# ----------------------------------------------------------------------
def _fmt_si(x: float) -> str:
    for div, suf in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(x) >= div:
            return f"{x / div:.2f}{suf}"
    return f"{x:.0f}"


def render_costs(summary: Dict[str, Any], path: str = "") -> str:
    """Text table for ``report costs``."""
    spec = summary["peak_spec"]
    lines = []
    lines.append(
        f"=== lightgbm_tpu cost-model report{': ' + path if path else ''} ===")
    src = " (LIGHTGBM_TPU_PEAK_SPECS)" if spec.get("source") == "env" else ""
    lines.append(
        f"peak spec [{spec['key']}{src}] for {spec['device_kind']}: "
        f"{_fmt_si(spec['flops_per_s'])}flop/s, "
        f"{_fmt_si(spec['hbm_bytes_per_s'])}B/s "
        f"(ridge AI {spec['flops_per_s'] / spec['hbm_bytes_per_s']:.1f} "
        f"flop/B)")
    rows = summary["table"]
    if rows:
        lines.append("")
        lines.append(f"{'phase':<16}{'program':<28}{'calls':>7}"
                     f"{'measured_s':>12}{'roofline_s':>12}{'eff%':>8}"
                     f"{'AI':>8}{'bound':>9}{'share%':>8}")
        for r in rows:
            eff = f"{r['efficiency_pct']:.2f}" \
                if r.get("efficiency_pct") is not None else "-"
            ai = f"{r['ai']:.2f}" if r.get("ai") is not None else "inf"
            lines.append(
                f"{r['phase']:<16}{r['program']:<28}{r['calls']:>7}"
                f"{r['measured_s']:>12.4f}{r['roofline_s']:>12.6f}"
                f"{eff:>8}{ai:>8}{r['bound']:>9}"
                f"{r['share_pct']:>8.1f}")
    else:
        lines.append("")
        lines.append("no joinable phases (trace has no jax_cost records, "
                     "or no spans matching a program's phase tag)")
    progs = summary["programs"]
    if progs:
        lines.append("")
        lines.append(
            f"program inventory ({summary['n_programs']} programs, "
            f"{summary['n_signatures']} signatures):")
        lines.append(f"{'program':<30}{'sigs':>6}{'flops/call':>12}"
                     f"{'bytes/call':>12}{'AI':>8}{'bound':>9}"
                     f"{'roofline_ms':>13}")
        for name, st in progs.items():
            ai = f"{st['ai']:.2f}" if st.get("ai") is not None else "inf"
            lines.append(
                f"{name:<30}{st['signatures']:>6}"
                f"{_fmt_si(st['flops_per_call']):>12}"
                f"{_fmt_si(st['bytes_per_call']):>12}{ai:>8}"
                f"{st['bound']:>9}"
                f"{1e3 * st['roofline_s_per_call']:>13.4f}")
    line = summary.get("next_target_line")
    if line:
        lines.append("")
        lines.append(line)
    return "\n".join(lines) + "\n"
