"""Stdlib-HTTP JSONL predict server — ``python -m lightgbm_tpu serve``.

Endpoints:
  POST /predict      body: one JSON row per line — either ``[f0, f1, ...]``
                     or ``{"features": [...]}``.  Response: one JSON
                     prediction per line, same order (a float, or a list
                     for multiclass), with the serving model version in
                     the ``X-Model-Version`` header (``?model_version=1``
                     additionally stamps every line as
                     ``{"prediction": ..., "model_version": N}`` — each
                     request is answered by exactly ONE version even
                     across a hot swap).  ``?raw_score=1`` skips the
                     objective's output conversion.
  POST /models       registry mode only: the body is a packed ``.npz``
                     artifact; it is validated, published into the model
                     registry as the next version, activated, and
                     hot-swapped into this replica without dropping a
                     request (serve/fleet.py).
  GET  /models       registry mode only: the registry listing plus the
                     version this replica is currently serving.
  GET  /healthz      liveness only: ``{"status": "ok"}`` whenever the
                     process answers.
  GET  /readyz       readiness: 200 once the artifact is loaded AND the
                     bucket-ladder warmup completed; 503 while warming
                     and again while draining — the signal a load
                     balancer keys traffic on.
  GET  /stats        serving metrics: batcher counters + latency
                     quantiles, bucket-cache compile accounting, queue
                     depth, readiness/drain state, registry staleness,
                     uptime.
  POST /fault        chaos drills (serve/faults.py): (re)arm serving
                     fault injection at runtime — ``{"spec":
                     "hang:1"}`` — an empty spec clears it; GET /fault
                     reports the armed spec + per-kind injection counts.
                     ``LIGHTGBM_TPU_SERVE_FAULT`` arms the same grammar
                     at startup.
  GET  /metrics      the same signals in Prometheus text format
                     (obs/metrics.py): request/shed/deadline counters,
                     batch-size + latency histograms, queue depth,
                     ready/draining/inflight state, XLA compile
                     accounting.  Rendering reads host counters only —
                     a scrape can never trigger an XLA compile.

Shutdown: SIGTERM starts a graceful drain — ``/readyz`` flips to 503,
new ``/predict`` requests get 503, in-flight microbatches finish
(bounded by ``drain_timeout_ms``), then the server exits 0.

Each HTTP request becomes one ``MicroBatcher.submit`` call, so
concurrent requests coalesce into shared device batches; an overloaded
queue answers 503 and an expired request deadline 504 (shed-not-queue,
see batcher.py).  A client (or proxy) ``X-Deadline-Ms`` header bounds
the request end to end: a spent budget 504s before any device work and
a live one caps the batcher queue wait at
``min(request_timeout_ms, remaining budget)``.

Startup: ``model=`` accepts either a packed ``.npz`` artifact
(serve/artifact.py) or a reference-format model text file, which is
packed on the fly.  Unless ``warmup=0``, the bucket ladder is
precompiled before the socket starts accepting, so the first real
request never pays an XLA compile.

Registry mode (``registry=dir``): the replica serves the registry's
active version and polls ``watch_token()`` every ``registry_poll_ms``;
when a publisher (another process, or ``POST /models`` on any replica
sharing the directory) activates a new version, the replica hot-swaps
to it at a microbatch boundary with zero dropped requests — and, for a
same-shape retrain, zero new XLA compiles (serve/compilecache.py tree
shape buckets).  An empty registry is seeded from ``model=`` when
given.
"""

from __future__ import annotations

import json
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import numpy as np

from ..obs import compilewatch, tracer
from ..obs.metrics import registry as metrics_registry
from ..utils.log import LightGBMError, Log
from . import faults
from .artifact import PackedPredictor, PredictorArtifact
from .batcher import MicroBatcher, RequestTimeout, ServerOverloaded
from .fleet import SwappablePredictor
from .registry import ModelRegistry

DEFAULTS = {
    "port": 9090,
    "max_batch_size": 1024,
    "max_delay_ms": 2.0,
    "max_queue_rows": 8192,
    "request_timeout_ms": 2000,
    "warmup": 1,
    "warmup_max_rows": 4096,
    "shard": 0,
    "drain_timeout_ms": 10000,
    "registry_poll_ms": 500.0,
    "pin_version": 0,
    "route_budget_mb": 0.0,
}

# per-version serving attribution (docs/FACTORY.md): one labeled child
# per model version currently loaded — the canary verdict's scrape
# surface.  Families are pruned back to the live version after every
# completed swap, so label cardinality stays bounded by the versions
# this replica is actually serving.
_M_VER_REQS = metrics_registry.labeled_counter(
    "lightgbm_tpu_serve_version_requests_total",
    "predict requests answered, split by serving model version")
_M_VER_ERRS = metrics_registry.labeled_counter(
    "lightgbm_tpu_serve_version_errors_total",
    "failed predict requests (500/503/504), split by model version")
_M_VER_LATENCY = metrics_registry.labeled_histogram(
    "lightgbm_tpu_serve_version_latency_seconds",
    "predict request latency, split by serving model version")

# per-route attribution (multi-model serving): one labeled child per
# route currently admitted ("default" is the unnamed /predict route).
# Families are pruned to the live route set on every route sync, so
# cardinality stays bounded by what this replica actually serves.
_M_ROUTE_REQS = metrics_registry.labeled_counter(
    "lightgbm_tpu_serve_route_requests_total",
    "predict requests answered, split by model route", label="model_route")
_M_ROUTE_ERRS = metrics_registry.labeled_counter(
    "lightgbm_tpu_serve_route_errors_total",
    "failed predict requests (500/503/504), split by model route",
    label="model_route")
_M_ROUTE_LATENCY = metrics_registry.labeled_histogram(
    "lightgbm_tpu_serve_route_latency_seconds",
    "predict request latency, split by model route", label="model_route")
_M_ADMISSION_REFUSED = metrics_registry.counter(
    "lightgbm_tpu_serve_admission_refused_total",
    "route admissions refused by the device-bytes budget")
_M_DEADLINE_REJECTED = metrics_registry.counter(
    "lightgbm_tpu_serve_deadline_rejected_total",
    "predicts 504ed because the X-Deadline-Ms budget was already spent")
_M_FAULTS_INJECTED = metrics_registry.counter(
    "lightgbm_tpu_serve_fault_injected_total",
    "requests wounded by LIGHTGBM_TPU_SERVE_FAULT / POST /fault")

_DEFAULT_ROUTE = "default"


def load_artifact(model_path: str) -> PredictorArtifact:
    """Load a packed ``.npz`` artifact, or pack a model text file."""
    if model_path.endswith(".npz"):
        return PredictorArtifact.load(model_path)
    from ..basic import Booster

    return PredictorArtifact.from_booster(Booster(model_file=model_path))


def make_predictor(artifact: PredictorArtifact,
                   shard: bool = False) -> PackedPredictor:
    predictor = PackedPredictor(artifact)
    if shard:
        if predictor.quantized:
            from .compilecache import BucketedQuantizedPredictor

            predictor.raw = BucketedQuantizedPredictor.from_qtree_arrays(
                predictor.artifact.arrays,
                predictor.artifact.num_tree_per_iteration, shard=True
            )
        else:
            from .compilecache import BucketedRawPredictor

            predictor.raw = BucketedRawPredictor.from_tree_arrays(
                artifact.arrays, artifact.num_tree_per_iteration, shard=True
            )
    return predictor


def load_predictor(model_path: str, shard: bool = False) -> PackedPredictor:
    return make_predictor(load_artifact(model_path), shard=shard)


def _parse_rows(body: bytes) -> np.ndarray:
    rows: List[List[float]] = []
    width = None
    for ln, line in enumerate(body.decode("utf-8").splitlines()):
        line = line.strip()
        if not line:
            continue
        row = json.loads(line)
        if isinstance(row, dict):
            row = row.get("features")
        if not isinstance(row, list):
            raise ValueError(f"line {ln + 1}: expected a JSON array of features")
        if width is None:
            width = len(row)
        elif len(row) != width:
            raise ValueError(
                f"line {ln + 1}: ragged request ({len(row)} features, "
                f"expected {width})"
            )
        rows.append([float(v) for v in row])
    if not rows:
        raise ValueError("empty request body")
    return np.asarray(rows, np.float64)


class _RouteSlot:
    """One admitted named route: its hot-swap slot + its own batcher
    pair, sharing the process-wide bucketed compile cache with every
    other route (same-shape models share every XLA program)."""

    __slots__ = ("route", "swapper", "batcher", "raw_batcher")

    def __init__(self, route: str, swapper, batcher_opts: Dict):
        self.route = route
        self.swapper = swapper
        self.batcher = MicroBatcher(
            lambda batch: swapper.predict(batch), **batcher_opts)
        self.raw_batcher = MicroBatcher(
            lambda batch: swapper.predict(batch, raw_score=True),
            **batcher_opts)

    def close(self) -> None:
        self.batcher.close()
        self.raw_batcher.close()


class PredictServer(ThreadingHTTPServer):
    """HTTP server owning the predictor + batcher; ``daemon_threads`` so
    in-flight handler threads never block shutdown."""

    daemon_threads = True

    def __init__(self, addr, predictor,
                 batcher_opts: Optional[Dict] = None,
                 registry: Optional[ModelRegistry] = None,
                 registry_poll_ms: float = 500.0,
                 warmup_max_rows: int = 4096, do_warmup: bool = True,
                 pin_version: Optional[int] = None,
                 route_budget_bytes: int = 0,
                 predictor_factory=None):
        self.predictor = predictor
        # pinned replicas (canary) serve exactly one version: no
        # watcher, and maybe_swap is a no-op even on POST /models
        self.pin_version = int(pin_version) if pin_version else None
        opts = dict(batcher_opts or {})
        self._batcher_opts = opts
        self.batcher = MicroBatcher(
            lambda batch: predictor.predict(batch),
            **opts,
        )
        self.raw_batcher = MicroBatcher(
            lambda batch: predictor.predict(batch, raw_score=True),
            **opts,
        )
        # multi-model: named routes from the registry's route table,
        # each a _RouteSlot admitted against the device-bytes budget
        # (0 = unlimited); refused routes answer 503 with the reason
        self.routes: Dict[str, _RouteSlot] = {}
        self.route_budget_bytes = max(0, int(route_budget_bytes))
        self.admission_refused: Dict[str, str] = {}
        self._route_lock = threading.Lock()
        self._predictor_factory = predictor_factory or PackedPredictor
        self.registry = registry
        self.registry_poll_ms = float(registry_poll_ms)
        self._warmup_max_rows = int(warmup_max_rows)
        self._do_warmup = bool(do_warmup)
        self._swap_lock = threading.Lock()
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        self.t_start = time.time()
        # readiness/drain state (docs/ROBUSTNESS.md): ready flips on
        # once the artifact is loaded and warmup completed; draining
        # flips /readyz and /predict to 503 while in-flight batches run;
        # drained marks a COMPLETED drain (draining settles back to
        # False so the state gauges read a stable zero — the satellite-2
        # accounting contract)
        self.ready = False
        self.draining = False
        self.drained = False
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        # scrape-time state gauges: evaluated inside /metrics rendering,
        # zero cost between scrapes (fn re-registration means the latest
        # server instance in a process owns the gauge)
        metrics_registry.gauge(
            "lightgbm_tpu_serve_ready",
            "1 once the artifact is loaded and warmup completed",
            fn=lambda: 1.0 if self.ready else 0.0)
        metrics_registry.gauge(
            "lightgbm_tpu_serve_draining",
            "1 while a SIGTERM graceful drain is in progress",
            fn=lambda: 1.0 if self.draining else 0.0)
        metrics_registry.gauge(
            "lightgbm_tpu_serve_inflight_requests",
            "HTTP predict requests currently being handled",
            fn=lambda: float(self._inflight))
        metrics_registry.gauge(
            "lightgbm_tpu_serve_uptime_seconds",
            "seconds since this server process started serving",
            fn=lambda: time.time() - self.t_start)
        # registry-staleness degradation (docs/ROBUSTNESS.md): a replica
        # whose swaps keep failing serves last-good FOREVER — correct,
        # but it must be visible, and the factory refuses to promote
        # against it (factory/supervisor.py _fleet_fresh)
        self._registry_stale_lock = threading.Lock()
        self._registry_stale_since: Optional[float] = None
        self._registry_failures = 0
        if registry is not None:
            # scrape-time registry views: a manifest read is host-side
            # file I/O only (never jax), cheap enough per scrape
            metrics_registry.gauge(
                "lightgbm_tpu_registry_models",
                "artifact versions published in the model registry",
                fn=lambda: float(len(registry.read_manifest()["entries"])))
            metrics_registry.gauge(
                "lightgbm_tpu_registry_active_version",
                "version the registry manifest currently activates",
                fn=lambda: float(registry.active_version() or 0))
            metrics_registry.gauge(
                "lightgbm_tpu_serve_registry_stale_seconds",
                "seconds since registry swaps started failing on this "
                "replica (0 = fresh)",
                fn=lambda: self.registry_stale_seconds())
        super().__init__(addr, _Handler)

    # -- registry staleness --------------------------------------------
    def registry_stale_seconds(self) -> float:
        with self._registry_stale_lock:
            if self._registry_stale_since is None:
                return 0.0
            return max(0.0, time.monotonic() - self._registry_stale_since)

    def _registry_sync_failed(self, err: Exception) -> None:
        with self._registry_stale_lock:
            self._registry_failures += 1
            n = self._registry_failures
            if self._registry_stale_since is None:
                self._registry_stale_since = time.monotonic()
        tracer.event("serve.registry_stale", consecutive_failures=n,
                     error=f"{type(err).__name__}: {err}")

    def _registry_sync_ok(self) -> None:
        with self._registry_stale_lock:
            was_stale = self._registry_stale_since is not None
            self._registry_stale_since = None
            self._registry_failures = 0
        if was_stale:
            Log.info("serve: registry sync recovered (fresh again)")

    # -- registry / hot swap -------------------------------------------
    def maybe_swap(self) -> Optional[Dict]:
        """Hot-swap to the registry's active version if it differs from
        the one serving.  Serialized so the watcher thread and a POST
        /models handler cannot double-load; returns the swap stats, or
        None when already current (or not in registry mode)."""
        if self.registry is None or self.pin_version is not None:
            return None
        with self._swap_lock:
            target = self.registry.active_version()
            if target is None or target == self.predictor.version:
                return None
            artifact = self.registry.load(target)
            stats = self.predictor.swap_to(
                artifact, target, warmup_max_rows=self._warmup_max_rows,
                do_warmup=self._do_warmup)
            # swap_to returned => the old version finished draining; its
            # labeled children would otherwise accumulate forever
            for fam in (_M_VER_REQS, _M_VER_ERRS, _M_VER_LATENCY):
                fam.prune({str(target)})
            return stats

    # -- multi-model routes --------------------------------------------
    def device_bytes_used(self) -> int:
        """Device-resident tree bytes across the default predictor and
        every admitted route — the admission accounting base."""
        used = int(getattr(self.predictor, "predictor",
                           self.predictor).device_bytes)
        for slot in self.routes.values():
            used += int(slot.swapper.predictor.device_bytes)
        return used

    def sync_routes(self) -> Optional[Dict]:
        """Reconcile the served route slots against the registry's route
        table: admit new routes (against the device-bytes budget),
        independently hot-swap routes whose version moved, tear down
        removed routes (and prune their metric children).  Returns a
        summary dict, or None when not in registry mode."""
        if self.registry is None or self.pin_version is not None:
            return None
        with self._route_lock:
            want = self.registry.routes()
            for name in list(self.routes):
                if name not in want:
                    slot = self.routes.pop(name)
                    slot.close()
                    self.admission_refused.pop(name, None)
                    tracer.event("serve.route_removed", route=name)
            for name, version in sorted(want.items()):
                slot = self.routes.get(name)
                try:
                    if slot is not None:
                        if slot.swapper.version != version:
                            artifact = self.registry.load(version)
                            slot.swapper.swap_to(
                                artifact, version,
                                warmup_max_rows=self._warmup_max_rows,
                                do_warmup=self._do_warmup)
                        continue
                    artifact = self.registry.load(version)
                    need = artifact.device_bytes_estimate()
                    used = self.device_bytes_used()
                    budget = self.route_budget_bytes
                    if budget and used + need > budget:
                        reason = (
                            f"route {name!r} (v{version}) needs {need} "
                            f"device bytes but {used} of the {budget}-byte "
                            f"budget are in use — remove a route or raise "
                            f"route_budget_mb")
                        if self.admission_refused.get(name) != reason:
                            Log.warning("serve: ADMISSION REFUSED: %s",
                                        reason)
                            _M_ADMISSION_REFUSED.inc()
                            tracer.event("serve.route_refused", route=name,
                                         version=int(version),
                                         need_bytes=int(need),
                                         used_bytes=int(used),
                                         budget_bytes=int(budget))
                        self.admission_refused[name] = reason
                        continue
                    swapper = SwappablePredictor(
                        self._predictor_factory(artifact), version=version)
                    if self._do_warmup:
                        swapper.warmup(self._warmup_max_rows)
                    self.routes[name] = _RouteSlot(name, swapper,
                                                   self._batcher_opts)
                    self.admission_refused.pop(name, None)
                    tracer.event("serve.route_added", route=name,
                                 version=int(version),
                                 device_bytes=int(
                                     swapper.predictor.device_bytes))
                except LightGBMError as e:
                    # a torn publish/corrupt artifact on ONE route must
                    # not take down the others — skip and retry on the
                    # next registry change
                    Log.warning("serve: route %r sync failed: %s", name, e)
            live = set(self.routes) | {_DEFAULT_ROUTE}
            for fam in (_M_ROUTE_REQS, _M_ROUTE_ERRS, _M_ROUTE_LATENCY):
                fam.prune(live)
            return {"routes": {n: s.swapper.version
                               for n, s in self.routes.items()},
                    "refused": dict(self.admission_refused)}

    def start_registry_watcher(self) -> None:
        """Poll the registry's change token and swap on activation —
        inotify-free, so it works on any shared filesystem."""
        if (self.registry is None or self.pin_version is not None
                or self._watch_thread is not None):
            return
        poll_s = max(self.registry_poll_ms, 1.0) / 1e3

        def _loop():
            token = self.registry.watch_token()
            while not self._watch_stop.wait(poll_s):
                t = self.registry.watch_token()
                if t == token:
                    continue
                token = t
                failed = None
                try:
                    self.maybe_swap()
                except Exception as e:
                    # a torn publish or corrupt artifact must not kill
                    # the serving loop — keep the current model and retry
                    # on the next token change
                    failed = e
                    Log.warning("serve: registry swap failed (still on "
                                "v%s): %s", getattr(self.predictor,
                                                    "version", "?"), e)
                try:
                    self.sync_routes()
                except Exception as e:
                    failed = e
                    Log.warning("serve: route sync failed: %s", e)
                if failed is None:
                    self._registry_sync_ok()
                else:
                    self._registry_sync_failed(failed)

        self._watch_thread = threading.Thread(
            target=_loop, name="ltpu-registry-watch", daemon=True)
        self._watch_thread.start()

    # -- in-flight request accounting ----------------------------------
    def track_begin(self) -> None:
        with self._inflight_cv:
            self._inflight += 1

    def track_end(self) -> None:
        with self._inflight_cv:
            self._inflight -= 1
            if self._inflight <= 0:
                self._inflight_cv.notify_all()

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Graceful shutdown: stop admitting work (``/readyz`` and
        ``/predict`` answer 503), wait for in-flight HTTP requests AND
        the batchers' queued/executing rows to finish (bounded by
        ``timeout_s``), then stop the accept loop and close the
        batchers.  Returns True when the drain completed with nothing in
        flight — in which case ``draining`` settles back to False (and
        ``drained`` latches True), so the inflight/draining gauges read
        a stable zero instead of being stuck at 1 forever."""
        self.draining = True
        deadline = time.monotonic() + float(timeout_s)
        with self._inflight_cv:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._inflight_cv.wait(min(remaining, 0.1))
            drained = self._inflight == 0
        # settle the batchers too: every queued AND executing row must
        # reach zero before the drain counts as complete
        batchers = [self.batcher, self.raw_batcher]
        for slot in list(self.routes.values()):
            batchers += [slot.batcher, slot.raw_batcher]
        for b in batchers:
            remaining = max(0.0, deadline - time.monotonic())
            drained = b.drain(remaining) and drained
        if not drained:
            Log.warning("serve: drain timed out with %d request(s) in "
                        "flight", self._inflight)
        self.shutdown()
        if drained:
            self.draining = False
        self.drained = True
        return drained

    def version_stats(self) -> Dict[str, Dict]:
        """Per-version serving attribution — the JSON parity view of the
        labeled ``/metrics`` families (same counters, same histogram).
        This is what the factory's canary observer polls for its SLO
        verdict."""
        out: Dict[str, Dict] = {}
        lat = _M_VER_LATENCY.children()
        errs = _M_VER_ERRS.children()
        for v, c in _M_VER_REQS.children().items():
            h = lat.get(v)
            out[v] = {
                "requests": int(c.value()),
                "errors": int(errs[v].value()) if v in errs else 0,
                "latency_p50_ms":
                    round(h.quantile(0.5) * 1e3, 3) if h else 0.0,
                "latency_p99_ms":
                    round(h.quantile(0.99) * 1e3, 3) if h else 0.0,
            }
        for v, c in errs.items():
            if v not in out:
                out[v] = {"requests": 0, "errors": int(c.value()),
                          "latency_p50_ms": 0.0, "latency_p99_ms": 0.0}
        return out

    def route_stats(self) -> Dict[str, Dict]:
        """Per-route serving attribution — the JSON parity view of the
        ``model_route``-labeled ``/metrics`` families (same counters,
        same histogram), pinned by tests/test_fleet.py."""
        out: Dict[str, Dict] = {}
        lat = _M_ROUTE_LATENCY.children()
        errs = _M_ROUTE_ERRS.children()
        for r, c in _M_ROUTE_REQS.children().items():
            h = lat.get(r)
            out[r] = {
                "requests": int(c.value()),
                "errors": int(errs[r].value()) if r in errs else 0,
                "latency_p50_ms":
                    round(h.quantile(0.5) * 1e3, 3) if h else 0.0,
                "latency_p99_ms":
                    round(h.quantile(0.99) * 1e3, 3) if h else 0.0,
            }
        for r, c in errs.items():
            if r not in out:
                out[r] = {"requests": 0, "errors": int(c.value()),
                          "latency_p50_ms": 0.0, "latency_p99_ms": 0.0}
        return out

    def stats(self) -> Dict:
        cw = compilewatch.snapshot()
        watched = cw["watched"].get("serve.predict_raw", {})
        qwatched = cw["watched"].get("serve.qpredict", {})
        out = {
            "uptime_s": round(time.time() - self.t_start, 1),
            "ready": self.ready,
            "draining": self.draining,
            "drained": self.drained,
            "inflight": self._inflight,
            "num_features": self.predictor.num_features,
            "num_class": self.predictor.artifact.num_class,
            "model_version": getattr(self.predictor, "version", None),
            "pin_version": self.pin_version,
            "per_version": self.version_stats(),
            "batcher": self.batcher.stats(),
            "raw_batcher": self.raw_batcher.stats(),
            "compiles": {
                "backend_compiles": cw["backend_compiles"],
                "predict_calls": watched.get("calls", 0),
                "predict_compiles": watched.get("compiles", 0),
                "predict_retraces": watched.get("retraces", 0),
                "qpredict_calls": qwatched.get("calls", 0),
                "qpredict_compiles": qwatched.get("compiles", 0),
                "qpredict_retraces": qwatched.get("retraces", 0),
            },
        }
        if self.routes or self.admission_refused or self.route_budget_bytes:
            with self._route_lock:
                out["routes"] = {
                    name: {
                        "version": slot.swapper.version,
                        "quantized": bool(getattr(
                            slot.swapper.predictor, "quantized", False)),
                        "device_bytes": getattr(
                            slot.swapper.predictor, "device_bytes", 0),
                        "swaps": slot.swapper.swaps,
                        "batcher": slot.batcher.stats(),
                    }
                    for name, slot in self.routes.items()
                }
            out["per_route"] = self.route_stats()
            out["admission"] = {
                "budget_bytes": self.route_budget_bytes,
                "used_bytes": self.device_bytes_used(),
                "refused": dict(self.admission_refused),
            }
        if isinstance(self.predictor, SwappablePredictor):
            out["swap"] = {
                "swaps": self.predictor.swaps,
                "draining_versions": self.predictor.draining_versions,
                "last": self.predictor.last_swap,
            }
        if self.registry is not None:
            with self._registry_stale_lock:
                failures = self._registry_failures
            out["registry"] = {
                "dir": self.registry.dir,
                "active_version": self.registry.active_version(),
                "models": len(self.registry.read_manifest()["entries"]),
                "stale_seconds": round(self.registry_stale_seconds(), 3),
                "consecutive_failures": failures,
            }
        fault = faults.counters()
        if fault["spec"]:
            out["fault"] = fault
        return out

    def shutdown(self):
        self._watch_stop.set()
        super().shutdown()
        self.batcher.close()
        self.raw_batcher.close()
        for slot in list(self.routes.values()):
            slot.close()


class _Handler(BaseHTTPRequestHandler):
    server_version = "lightgbm-tpu-serve/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route access logs to our logger
        Log.debug("serve: " + fmt, *args)

    def _reply(self, code: int, payload: bytes,
               ctype: str = "application/json",
               extra_headers: Optional[List] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        for k, v in extra_headers or []:
            self.send_header(k, str(v))
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _reply_json(self, code: int, obj) -> None:
        self._reply(code, (json.dumps(obj) + "\n").encode())

    def do_GET(self):
        if self.path == "/healthz":
            self._reply_json(200, {"status": "ok"})
        elif self.path == "/readyz":
            if self.server.drained:
                self._reply_json(503, {"status": "stopped"})
            elif self.server.draining:
                self._reply_json(503, {"status": "draining"})
            elif not self.server.ready:
                self._reply_json(503, {"status": "warming"})
            else:
                self._reply_json(200, {"status": "ready"})
        elif self.path == "/stats":
            self._reply_json(200, self.server.stats())
        elif self.path == "/models":
            if self.server.registry is None:
                self._reply_json(404, {"error": "no model registry "
                                                "(start with registry=dir)"})
            else:
                self._reply_json(200, {
                    "models": self.server.registry.list_models(),
                    "active_version": self.server.registry.active_version(),
                    "serving_version": getattr(self.server.predictor,
                                               "version", None),
                    "routes": self.server.registry.routes(),
                })
        elif self.path == "/routes":
            self._do_routes_get()
        elif self.path == "/fault":
            self._reply_json(200, faults.counters())
        elif self.path == "/metrics":
            # Prometheus text format; render() never touches jax, so a
            # scrape storm cannot compile or serialize device work
            self._reply(200, metrics_registry.render().encode(),
                        ctype="text/plain; version=0.0.4; charset=utf-8")
        else:
            self._reply_json(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        path, _, query = self.path.partition("?")
        if path == "/models":
            self._do_publish()
            return
        if path == "/routes":
            self._do_routes_post()
            return
        if path == "/fault":
            self._do_fault()
            return
        route = None
        if path.startswith("/predict/"):
            route = path[len("/predict/"):]
        elif path != "/predict":
            self._reply_json(404, {"error": f"unknown path {path}"})
            return
        if self.server.draining or self.server.drained:
            # shed-not-queue during drain: the LB already saw /readyz
            # flip; anything still arriving is told to go elsewhere
            self._reply_json(503, {"error": "server is draining"})
            return
        # serving fault injection (serve/faults.py): wound the request
        # BEFORE inflight tracking so a hung drill never wedges a drain;
        # admin endpoints above stay exempt so a chaos test can always
        # clear the fault it armed
        act = faults.action()
        if act is not None:
            _M_FAULTS_INJECTED.inc()
            tracer.event("serve.fault", kind=act[0])
            if act[0] == "hang":
                # the canonical gray failure: the connection stays open,
                # /readyz stays 200, no response ever comes (bounded
                # only so the daemon thread eventually dies in tests)
                time.sleep(3600.0)
                return
            if act[0] == "error":
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    self.rfile.read(length)  # keep the connection parseable
                self._count_error(route)
                self._reply_json(500, {"error": "injected serve fault"})
                return
            if act[0] == "delay":
                time.sleep(act[1] / 1e3)
        self.server.track_begin()
        try:
            self._do_predict(query, route=route)
        finally:
            self.server.track_end()

    def _do_fault(self) -> None:
        """POST /fault {"spec": "hang:1,..."} — (re)arm serving fault
        injection at runtime; an empty spec clears it.  The chaos
        harness measures a healthy baseline on a fleet, then wounds the
        very same replicas through this endpoint."""
        try:
            length = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(length) or b"{}")
            spec = str(body.get("spec") or "")
        except (ValueError, json.JSONDecodeError) as e:
            self._reply_json(400, {"error": f"bad request body: {e}"})
            return
        try:
            armed = faults.set_spec(spec)
        except ValueError as e:
            self._reply_json(400, {"error": str(e)})
            return
        self._reply_json(200, {"spec": armed})

    def _do_routes_get(self) -> None:
        """GET /routes: the live route table (what THIS replica serves)
        plus the admission ledger — budget, usage, and refusals."""
        with self.server._route_lock:
            table = {name: {"version": slot.swapper.version,
                            "quantized": bool(getattr(
                                slot.swapper.predictor, "quantized", False)),
                            "device_bytes": getattr(
                                slot.swapper.predictor, "device_bytes", 0)}
                     for name, slot in self.server.routes.items()}
        self._reply_json(200, {
            "routes": table,
            "registry_routes": (self.server.registry.routes()
                                if self.server.registry is not None else {}),
            "admission": {
                "budget_bytes": self.server.route_budget_bytes,
                "used_bytes": self.server.device_bytes_used(),
                "refused": dict(self.server.admission_refused),
            },
        })

    def _do_routes_post(self) -> None:
        """POST /routes admin endpoint (registry mode only).

        ``{"route": name, "version": v}`` binds the route to a published
        version; ``{"route": name, "remove": true}`` unbinds it.  Either
        way the local reconciler runs synchronously so the reply reflects
        this replica's actual serving state (other replicas converge via
        their registry watcher).
        """
        if self.server.registry is None:
            self._reply_json(404, {"error": "no model registry "
                                            "(start with registry=dir)"})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(length) or b"{}")
            route = str(body["route"])
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            self._reply_json(400, {"error": f"bad request body: {e}"})
            return
        try:
            if body.get("remove"):
                removed = self.server.registry.remove_route(route)
                if not removed:
                    self._reply_json(404,
                                     {"error": f"unknown route {route!r}"})
                    return
            else:
                self.server.registry.set_route(route, int(body["version"]))
        except (LightGBMError, TimeoutError, KeyError, ValueError) as e:
            self._reply_json(400, {"error": str(e)})
            return
        sync = None
        try:
            sync = self.server.sync_routes()
        except Exception as e:
            Log.warning("serve: route sync after POST /routes failed: %s", e)
        self._reply_json(200, {
            "route": route,
            "registry_routes": self.server.registry.routes(),
            "sync": sync,
        })

    def _do_publish(self) -> None:
        """POST /models: validate + publish the uploaded artifact bytes,
        then hot-swap this replica to it (other replicas polling the
        shared registry follow within their poll interval)."""
        if self.server.registry is None:
            self._reply_json(404, {"error": "no model registry "
                                            "(start with registry=dir)"})
            return
        length = int(self.headers.get("Content-Length") or 0)
        blob = self.rfile.read(length) if length else b""
        if not blob:
            self._reply_json(400, {"error": "empty artifact upload"})
            return
        try:
            version = self.server.registry.publish_bytes(blob)
        except (LightGBMError, TimeoutError) as e:
            self._reply_json(400, {"error": str(e)})
            return
        swap = None
        try:
            swap = self.server.maybe_swap()
        except Exception as e:
            Log.warning("serve: swap to freshly published v%d failed: %s",
                        version, e)
        self._reply_json(200, {
            "version": version,
            "active_version": self.server.registry.active_version(),
            "serving_version": getattr(self.server.predictor, "version",
                                       None),
            "swap": swap,
        })

    def _count_error(self, route: Optional[str] = None) -> None:
        # a failed request never reached a batch, so it is attributed
        # to the version currently serving
        _M_VER_ERRS.labels(
            getattr(self.server.predictor, "version", 0)).inc()
        _M_ROUTE_ERRS.labels(route if route is not None else
                             _DEFAULT_ROUTE).inc()

    def _do_predict(self, query: str, route: Optional[str] = None) -> None:
        # deadline propagation: the proxy forwards the SHRUNKEN client
        # budget in X-Deadline-Ms; a spent budget 504s before any row
        # parsing or device work, and a live one bounds the batcher wait
        t_arrive = time.monotonic()
        budget_ms: Optional[float] = None
        raw_budget = self.headers.get("X-Deadline-Ms")
        if raw_budget:
            try:
                budget_ms = float(raw_budget)
            except ValueError:
                budget_ms = None
        if budget_ms is not None and budget_ms <= 0:
            _M_DEADLINE_REJECTED.inc()
            self._count_error(route)
            self._reply_json(504, {"error": "deadline exhausted before "
                                            "any device work"})
            return
        raw_score = "raw_score=1" in query
        stamp_version = "model_version=1" in query
        if route is None:
            batcher_pair = (self.server.batcher, self.server.raw_batcher)
        else:
            with self.server._route_lock:
                slot = self.server.routes.get(route)
                refused = self.server.admission_refused.get(route)
            if slot is None:
                if refused is not None:
                    # admitted-by-name but not by budget: loud, actionable
                    self._reply_json(503, {"error": f"route {route!r} "
                                           f"refused admission: {refused}"})
                else:
                    self._reply_json(404,
                                     {"error": f"unknown route {route!r}"})
                return
            batcher_pair = (slot.batcher, slot.raw_batcher)
        batcher = batcher_pair[1] if raw_score else batcher_pair[0]
        route_label = route if route is not None else _DEFAULT_ROUTE
        try:
            length = int(self.headers.get("Content-Length") or 0)
            rows = _parse_rows(self.rfile.read(length))
        except (ValueError, json.JSONDecodeError) as e:
            self._reply_json(400, {"error": str(e)})
            return
        t0 = time.monotonic()
        timeout_ms: Optional[float] = None
        if budget_ms is not None:
            remaining = budget_ms - (time.monotonic() - t_arrive) * 1e3
            # the batcher queue wait takes min(local timeout, remaining
            # budget); an already-spent budget fast-fails inside _submit
            timeout_ms = min(float(batcher.request_timeout_ms), remaining)
        try:
            preds, version = batcher.submit_ex(rows, timeout_ms=timeout_ms)
        except ServerOverloaded as e:
            self._count_error(route)
            self._reply_json(503, {"error": str(e)})
            return
        except RequestTimeout as e:
            self._count_error(route)
            self._reply_json(504, {"error": str(e)})
            return
        except Exception as e:
            Log.warning("serve: predict failed: %s", e)
            self._count_error(route)
            self._reply_json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        # attribute the request to the ONE version that answered it —
        # the same version the X-Model-Version header carries — and to
        # the route the caller addressed ("default" for bare /predict)
        _M_VER_REQS.labels(version).inc()
        _M_VER_LATENCY.labels(version).observe(time.monotonic() - t0)
        _M_ROUTE_REQS.labels(route_label).inc()
        _M_ROUTE_LATENCY.labels(route_label).observe(time.monotonic() - t0)

        def _plain(p):
            return p.tolist() if isinstance(p, np.ndarray) else float(p)

        if stamp_version:
            lines = [json.dumps({"prediction": _plain(p),
                                 "model_version": version})
                     for p in preds]
        else:
            lines = [json.dumps(_plain(p)) for p in preds]
        headers = ([("X-Model-Version", int(version))]
                   if version is not None else [])
        if route is not None:
            headers.append(("X-Model-Route", route))
        self._reply(200, ("\n".join(lines) + "\n").encode(),
                    ctype="application/jsonl", extra_headers=headers)


def make_server(model_path: Optional[str] = None, host: str = "127.0.0.1",
                port: int = 0, warmup_max_rows: int = 4096,
                shard: bool = False, do_warmup: bool = True,
                registry_dir: Optional[str] = None,
                registry_poll_ms: float = 500.0,
                pin_version: Optional[int] = None,
                route_budget_mb: float = 0.0,
                **batcher_opts) -> PredictServer:
    """Build (and optionally warm) a ready-to-run server; ``port=0``
    binds an ephemeral port (tests).  With ``registry_dir`` the server
    serves the registry's active version and hot-swaps on activation;
    an empty registry is seeded from ``model_path``.  ``pin_version``
    (registry mode) serves exactly that published version and never
    swaps — the factory's canary replica."""
    registry = ModelRegistry(registry_dir) if registry_dir else None
    version = 1
    if registry is not None:
        if pin_version:
            # canary replica: serve exactly this version, ignore
            # activations — promotion/rollback happens around us
            version = int(pin_version)
            artifact = registry.load(version)
        else:
            if registry.active_version() is None:
                if not model_path:
                    Log.fatal("serve: registry %s is empty and no model= "
                              "was given to seed it", registry_dir)
                # lock-guarded: N replicas racing to seed the same shared
                # registry publish exactly one v1
                registry.seed(load_artifact(model_path))
            version, artifact = registry.load_active()
        predictor = make_predictor(artifact, shard=shard)
    else:
        if not model_path:
            Log.fatal("serve: need model=path.npz|model.txt (or "
                      "registry=dir)")
        predictor = load_predictor(model_path, shard=shard)
    swapper = SwappablePredictor(predictor, version=version)
    server = PredictServer((host, port), swapper, batcher_opts,
                           registry=registry,
                           registry_poll_ms=registry_poll_ms,
                           warmup_max_rows=warmup_max_rows,
                           do_warmup=do_warmup,
                           pin_version=pin_version,
                           route_budget_bytes=int(route_budget_mb * (1 << 20)),
                           predictor_factory=lambda art: make_predictor(
                               art, shard=shard))
    if do_warmup:
        stats = swapper.warmup(warmup_max_rows)
        Log.info("serve: warmup compiled %d programs over buckets %s in %.2fs",
                 stats["compiles"], stats["buckets"], stats["secs"])
    server.sync_routes()  # admit named routes before advertising ready
    server.ready = True  # artifact loaded + warmup complete -> /readyz 200
    if registry is not None:
        server.start_registry_watcher()
    return server


def main(argv: List[str]) -> int:
    """``python -m lightgbm_tpu serve model=... [key=value ...]``."""
    from ..cli import parse_argv

    tracer.refresh_from_env()
    faults.refresh_from_env()  # LIGHTGBM_TPU_SERVE_FAULT chaos drills
    params = parse_argv(argv)
    model_path = params.get("model") or params.get("input_model")
    registry_dir = params.get("registry")
    if not model_path and not registry_dir:
        Log.warning("serve: no model file (model=path.npz or model=model.txt"
                    ", or registry=dir)")
        return 1
    opts = dict(DEFAULTS)
    for k in list(opts):
        if k in params:
            opts[k] = type(opts[k])(float(params[k]))
    server = make_server(
        model_path,
        host=str(params.get("host", "127.0.0.1")),
        port=int(opts["port"]),
        warmup_max_rows=int(opts["warmup_max_rows"]),
        shard=bool(opts["shard"]),
        do_warmup=bool(opts["warmup"]),
        registry_dir=registry_dir,
        registry_poll_ms=float(opts["registry_poll_ms"]),
        pin_version=int(opts["pin_version"]) or None,
        route_budget_mb=float(opts["route_budget_mb"]),
        max_batch_size=int(opts["max_batch_size"]),
        max_delay_ms=float(opts["max_delay_ms"]),
        max_queue_rows=int(opts["max_queue_rows"]),
        request_timeout_ms=float(opts["request_timeout_ms"]),
    )
    host, port = server.server_address[:2]
    Log.info("serve: listening on http://%s:%d (POST /predict, GET "
             "/healthz /readyz /stats)", host, port)

    drain_timeout_s = float(opts["drain_timeout_ms"]) / 1e3

    def _on_sigterm(signum, frame):
        # graceful drain off the signal context: flip /readyz, let
        # in-flight microbatches finish, then stop serve_forever
        Log.warning("serve: SIGTERM — draining (timeout %.1fs)",
                    drain_timeout_s)
        threading.Thread(target=server.drain, args=(drain_timeout_s,),
                         name="ltpu-serve-drain", daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # pragma: no cover - non-main thread (embedding)
        pass

    try:
        server.serve_forever()
    except KeyboardInterrupt:
        Log.info("serve: shutting down")
        server.shutdown()
    finally:
        server.server_close()
    Log.info("serve: drained and stopped")
    return 0
