"""Serving-plane fault injection (gray-failure drills).

Mirrors the ``parallel/net.py`` ``fault_point`` grammar for the SERVING
request path: ``LIGHTGBM_TPU_SERVE_FAULT`` arms a spec at replica start,
and ``POST /fault {"spec": ...}`` re-arms (or clears) it at runtime so a
chaos test can measure a healthy baseline on the very fleet it is about
to wound.  The replica's request handler calls :func:`action` once per
predict request and applies whatever fires:

    hang:N        every predict from request N on (1-based) never
                  answers — the canonical gray failure: the socket
                  accepts, ``/readyz`` stays 200, ``/predict`` wedges
    delay:ms      every predict stalls ``ms`` milliseconds before work
    delay:ms:frac deterministic fraction ``frac`` of predicts stall
                  (canary-tick arithmetic — no RNG, no bursts)
    error:N       every predict from request N on returns HTTP 500
    flap:s        alternate ``s`` seconds hanging / ``s`` seconds
                  healthy on the wall clock (hang phase first)

Specs are comma-separable; the first clause that fires wins.  With
nothing armed :func:`action` is a single attribute read — the off path
adds no measurable per-request overhead and responses are byte-identical
to a build without this module.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.log import Log

ENV_VAR = "LIGHTGBM_TPU_SERVE_FAULT"

_lock = threading.Lock()
_armed = False          # fast-path flag: False ⇒ action() returns None
_loaded = False         # env consulted at least once
_spec_str = ""
_spec: List[Tuple] = []
_requests = 0           # predicts seen while a spec was armed
_t_armed = 0.0          # monotonic arm time (flap phase origin)
_injected: Dict[str, int] = {}


def parse_serve_fault_spec(spec: str) -> List[Tuple]:
    """Parse ``hang:N|delay:ms[:frac]|error:N|flap:s`` (comma-separable)
    into clause tuples.  Raises ``ValueError`` on bad grammar — the env
    path warns-and-ignores, the ``/fault`` endpoint relays a 400."""
    out: List[Tuple] = []
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        kind = fields[0]
        try:
            if kind == "hang" and len(fields) == 2:
                out.append(("hang", int(fields[1])))
            elif kind == "error" and len(fields) == 2:
                out.append(("error", int(fields[1])))
            elif kind == "delay" and len(fields) in (2, 3):
                ms = float(fields[1])
                frac = float(fields[2]) if len(fields) == 3 else 1.0
                if ms < 0 or not (0.0 < frac <= 1.0):
                    raise ValueError(part)
                out.append(("delay", ms, frac))
            elif kind == "flap" and len(fields) == 2:
                s = float(fields[1])
                if s <= 0:
                    raise ValueError(part)
                out.append(("flap", s))
            else:
                raise ValueError(part)
        except ValueError:
            raise ValueError(
                f"bad serve fault clause {part!r} (want hang:N | "
                f"delay:ms[:frac] | error:N | flap:s)") from None
    return out


def set_spec(spec: Optional[str]) -> str:
    """Arm ``spec`` (empty/None clears).  Resets the per-spec request
    counter and flap clock.  Raises ``ValueError`` on bad grammar."""
    global _armed, _loaded, _spec_str, _spec, _requests, _t_armed
    clauses = parse_serve_fault_spec(spec or "")
    with _lock:
        _loaded = True
        _spec = clauses
        _spec_str = str(spec or "") if clauses else ""
        _requests = 0
        _injected.clear()
        _t_armed = time.monotonic()
        _armed = bool(clauses)
        if clauses:
            Log.warning("serve: FAULT INJECTION armed: %s", _spec_str)
    return _spec_str


def refresh_from_env() -> None:
    """Load ``LIGHTGBM_TPU_SERVE_FAULT`` (bad specs warn and stay off,
    like net.fault_point)."""
    global _loaded
    raw = os.environ.get(ENV_VAR, "")
    try:
        set_spec(raw)
    except ValueError as e:
        Log.warning("serve: ignoring bad %s: %s", ENV_VAR, e)
        with _lock:
            _loaded = True


def _ensure_loaded() -> None:
    if not _loaded:
        refresh_from_env()


def action() -> Optional[Tuple]:
    """The per-request hook: returns the firing clause — ``("hang",)``,
    ``("delay", ms)``, ``("error",)`` — or None.  First clause wins."""
    global _requests
    if _loaded and not _armed:
        return None
    _ensure_loaded()
    if not _armed:
        return None
    with _lock:
        _requests += 1
        n = _requests
        elapsed = time.monotonic() - _t_armed
        for clause in _spec:
            kind = clause[0]
            if kind == "hang" and n >= clause[1]:
                _injected["hang"] = _injected.get("hang", 0) + 1
                return ("hang",)
            if kind == "error" and n >= clause[1]:
                _injected["error"] = _injected.get("error", 0) + 1
                return ("error",)
            if kind == "delay":
                ms, frac = clause[1], clause[2]
                # canary-tick arithmetic: fires on exactly the requests
                # where floor(n*frac) advances — fraction frac, no RNG
                if int(n * frac) > int((n - 1) * frac):
                    _injected["delay"] = _injected.get("delay", 0) + 1
                    return ("delay", ms)
            if kind == "flap":
                if int(elapsed / clause[1]) % 2 == 0:
                    _injected["hang"] = _injected.get("hang", 0) + 1
                    return ("hang",)
    return None


def counters() -> Dict:
    """``/stats``/``GET /fault`` surface: the armed spec + what fired."""
    _ensure_loaded()
    with _lock:
        return {
            "spec": _spec_str,
            "requests_seen": int(_requests),
            "injected": dict(_injected),
        }
