"""TPU-native inference serving subsystem.

Training produces a ``Booster``; serving heavy traffic needs three more
things the training stack deliberately does not provide:

1. **Packed artifacts** (``artifact.py``) — the stacked SoA tree arrays
   (``ops/predict.TreeArrays``) plus objective/class/feature metadata
   frozen into one versioned ``.npz`` bundle.  A server cold-starts by
   memory-loading numpy arrays instead of reparsing model text through
   the host ``Tree`` builder.
2. **Shape-bucketed compile cache** (``compilecache.py``) — arbitrary
   request sizes are padded up a power-of-two bucket ladder so every
   batch shape hits one of a small fixed set of compiled programs;
   ``warmup()`` precompiles the ladder and the obs compile accountant
   flags anything that still compiles after it.
3. **Microbatching** (``batcher.py``) + a stdlib-HTTP front end
   (``server.py``, ``python -m lightgbm_tpu serve``) — concurrent
   requests coalesce into device-sized batches under
   ``max_batch_size``/``max_delay_ms`` with bounded queueing and
   overload shedding.
4. **Fleet scale-out** (``registry.py``, ``fleet.py``,
   ``python -m lightgbm_tpu fleet``) — a versioned on-disk model
   registry with atomic CRC'd publishes, zero-downtime hot swap at
   microbatch boundaries (zero new XLA compiles for same-shape
   retrains, courtesy of the tree-shape compile-cache buckets), and a
   replicated front end behind a health-checking load-balancing proxy.

See docs/SERVING.md for the artifact format and operational knobs.
"""

from .artifact import PackedPredictor, PredictorArtifact
from .batcher import MicroBatcher, RequestTimeout, ServerOverloaded
from .compilecache import (BucketedQuantizedPredictor, BucketedRawPredictor,
                           bucket_for, bucket_ladder, pad_qtree_arrays,
                           pad_tree_arrays, tree_shape_bucket)
from .fleet import FleetProxy, SwappablePredictor
from .registry import ModelRegistry

__all__ = [
    "PredictorArtifact",
    "PackedPredictor",
    "BucketedRawPredictor",
    "BucketedQuantizedPredictor",
    "bucket_for",
    "bucket_ladder",
    "tree_shape_bucket",
    "pad_tree_arrays",
    "pad_qtree_arrays",
    "MicroBatcher",
    "ServerOverloaded",
    "RequestTimeout",
    "ModelRegistry",
    "SwappablePredictor",
    "FleetProxy",
]
