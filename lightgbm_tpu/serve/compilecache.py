"""Shape-bucketed predict compile cache.

``ops/predict.predict_raw`` is an ordinary ``jax.jit`` program whose
cache key includes the batch shape: a server answering arbitrary request
sizes would recompile for every new N (seconds of XLA work on a latency
path).  Here every incoming batch is padded up a power-of-two bucket
ladder, so any request size N hits one of ``log2(max_rows)`` compiled
programs.  Padded rows are zeros; tree traversal is row-independent, so
real rows' outputs are bit-identical to an unpadded call and the padding
is stripped before returning.

``warmup()`` precompiles the whole ladder up front and reports through
the obs tracer; the module-level ``JitWatch`` wrapper flags any compile
that still happens after warmup as an unexpected retrace, which is the
serving-loop equivalent of the training-side retrace detector
(docs/OBSERVABILITY.md).

Multi-device hosts can traverse with the batch row-sharded over the
local mesh (``shard=True``): the bucket is padded to a multiple of the
device count and the data planes are placed with a ``NamedSharding``
over the ``parallel/`` one-axis mesh, letting XLA partition the
traversal; tree arrays are replicated once at construction.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from ..obs import JitWatch, tracer
from ..obs import compilewatch
from ..ops.predict import (LinearTreeArrays, TreeArrays, predict_raw,
                           predict_raw_linear)
from ..utils.log import Log

DEFAULT_MIN_BUCKET = 8

# the per-class tree-array arguments of predict_raw, in call order
# (after the three data planes)
_TREE_ARG_FIELDS = (
    "split_feature_real",
    "threshold_real",
    "threshold_real_lo",
    "threshold_real_lo2",
    "default_value_real",
    "default_value_real_lo",
    "default_value_real_lo2",
    "is_categorical",
    "left_child",
    "right_child",
    "leaf_value",
)

# the per-class tree-array arguments of predict_raw_linear, in call
# order (after the three data planes): the exact fields + the v3
# linear-leaf coefficient planes
_LINEAR_TREE_ARG_FIELDS = _TREE_ARG_FIELDS + (
    "leaf_feat_real",
    "leaf_feat_valid",
    "leaf_coeff",
    "leaf_const",
    "leaf_is_linear",
)

# the per-class tree-array arguments of qpredict_raw, in call order
# (after the rank-code matrix)
_Q_TREE_ARG_FIELDS = (
    "split_feature",
    "threshold_q",
    "default_q",
    "flags",
    "left_child",
    "right_child",
    "leaf_value",
)

# one shared watch: every bucketed predict in the process (Booster.predict
# and the serving subsystem) is accounted under "serve.predict_raw"
_watched_predict_raw: Optional[JitWatch] = None

# likewise for the quantized traversal, under "serve.qpredict"
_watched_qpredict: Optional[JitWatch] = None

# and the linear-leaf traversal, under "serve.predict_linear"
_watched_predict_linear: Optional[JitWatch] = None


def _watch() -> JitWatch:
    global _watched_predict_raw
    if _watched_predict_raw is None:
        _watched_predict_raw = JitWatch(predict_raw, "serve.predict_raw",
                                        phase="serve_batch")
    return _watched_predict_raw


def _lwatch() -> JitWatch:
    global _watched_predict_linear
    if _watched_predict_linear is None:
        _watched_predict_linear = JitWatch(
            predict_raw_linear, "serve.predict_linear",
            phase="serve_batch")
    return _watched_predict_linear


def _qwatch() -> JitWatch:
    global _watched_qpredict
    if _watched_qpredict is None:
        from ..ops.qpredict import qpredict_raw

        _watched_qpredict = JitWatch(qpredict_raw, "serve.qpredict",
                                     phase="serve_batch")
    return _watched_qpredict


def tree_shape_bucket(n: int) -> int:
    """Canonical padded size for a stacked-tree axis (node count M or
    leaf count L): the next power of two >= max(n, 2).

    The XLA program cache keys on argument SHAPES, so two models whose
    stacked arrays differ only in max-leaf count would compile twice —
    a retrain with identical ``num_trees/num_leaves`` config can land on
    M=14 where its predecessor had M=15 purely from data noise.  Padding
    both up the same ladder makes the compile cache effectively keyed on
    tree *shape class* instead of model identity: a hot swap to a
    same-shape retrain inherits every warm program (zero new compiles —
    the swap acceptance contract, pinned by tests/test_fleet.py).
    Padded node slots are unreachable (traversal starts at node 0 and
    only follows real child links) and padded leaf columns are never
    gathered, so outputs are bit-identical."""
    b = 2
    while b < n:
        b <<= 1
    return b


def pad_tree_arrays(arrays: TreeArrays) -> TreeArrays:
    """Pad a host-side ``TreeArrays`` to canonical shape buckets
    ((T, M) -> (T, bucket(M)), (T, L) -> (T, bucket(L))).  Returns the
    input unchanged when already canonical.  Opt out with
    ``LIGHTGBM_TPU_TREE_SHAPE_BUCKETS=0`` (exact observed shapes)."""
    import os

    if os.environ.get("LIGHTGBM_TPU_TREE_SHAPE_BUCKETS", "1") == "0":
        return arrays
    m = arrays.split_feature.shape[1]
    L = arrays.leaf_value.shape[1]
    mb, lb = tree_shape_bucket(m), tree_shape_bucket(L)
    if mb == m and lb == L:
        return arrays
    fields = {}
    for f in TreeArrays.FIELDS:
        a = np.asarray(getattr(arrays, f))
        pad = (lb if f == "leaf_value" else mb) - a.shape[1]
        fields[f] = np.pad(a, ((0, 0), (0, pad))) if pad else a
    return TreeArrays(**fields).validate()


def pad_linear_tree_arrays(arrays: LinearTreeArrays) -> LinearTreeArrays:
    """Linear counterpart of ``pad_tree_arrays``: the node/leaf planes
    pad to the same (T, bucket(M))/(T, bucket(L)) classes and the
    coefficient planes to (T, bucket(L), bucket(K)) — K (the max leaf
    path length) is data-dependent the same way M/L are, so it rides the
    same ladder to keep the zero-new-compile swap contract.  Padded
    coefficient slots are zero with ``leaf_feat_valid`` 0, so the padded
    dot product contributes exactly 0.  Same
    ``LIGHTGBM_TPU_TREE_SHAPE_BUCKETS=0`` opt-out."""
    import os

    if os.environ.get("LIGHTGBM_TPU_TREE_SHAPE_BUCKETS", "1") == "0":
        return arrays
    m = arrays.split_feature.shape[1]
    L = arrays.leaf_value.shape[1]
    k = arrays.leaf_coeff.shape[2]
    mb, lb = tree_shape_bucket(m), tree_shape_bucket(L)
    kb = tree_shape_bucket(k)
    if mb == m and lb == L and kb == k:
        return arrays
    fields = {}
    for f in LinearTreeArrays.FIELDS:
        a = np.asarray(getattr(arrays, f))
        if a.ndim == 3:
            fields[f] = np.pad(
                a, ((0, 0), (0, lb - a.shape[1]), (0, kb - a.shape[2])))
        else:
            pad = (lb if f in ("leaf_value", "leaf_const",
                               "leaf_is_linear") else mb) - a.shape[1]
            fields[f] = np.pad(a, ((0, 0), (0, pad))) if pad else a
    return LinearTreeArrays(**fields).validate()


def pad_qtree_arrays(arrays):
    """Quantized counterpart of ``pad_tree_arrays``: pad the narrow node
    planes to the same canonical (T, bucket(M))/(T, bucket(L)) shape
    classes AND round the static ``levels`` traversal bound up the same
    power-of-two ladder — ``levels`` is a static jit argument, so two
    same-shape models with depths 11 and 13 would otherwise compile two
    programs and break the zero-new-compile swap contract.  Extra
    iterations past a tree's real depth are no-ops (every row already
    sits on a leaf).  Same ``LIGHTGBM_TPU_TREE_SHAPE_BUCKETS=0``
    opt-out."""
    import os

    from ..ops.qpredict import QTreeArrays

    if os.environ.get("LIGHTGBM_TPU_TREE_SHAPE_BUCKETS", "1") == "0":
        return arrays
    m = arrays.split_feature.shape[1]
    L = arrays.leaf_value.shape[1]
    mb, lb = tree_shape_bucket(m), tree_shape_bucket(L)
    levels = tree_shape_bucket(arrays.levels)
    if mb == m and lb == L and levels == arrays.levels:
        return arrays
    fields = {}
    for f in QTreeArrays.NODE_FIELDS:
        a = np.asarray(getattr(arrays, f))
        pad = (lb if f == "leaf_value" else mb) - a.shape[1]
        fields[f] = np.pad(a, ((0, 0), (0, pad))) if pad else a
    for f in QTreeArrays.TABLE_FIELDS:
        fields[f] = getattr(arrays, f)
    return QTreeArrays(levels=levels, **fields).validate()


def bucket_for(n: int, min_bucket: int = DEFAULT_MIN_BUCKET,
               multiple_of: int = 1) -> int:
    """Smallest power-of-two >= max(n, min_bucket), rounded up to a
    multiple of ``multiple_of`` (device count when row-sharding)."""
    if n <= 0:
        n = 1
    b = max(int(min_bucket), 1)
    while b < n:
        b <<= 1
    if multiple_of > 1 and b % multiple_of:
        b += multiple_of - (b % multiple_of)
    return b


def bucket_ladder(max_rows: int, min_bucket: int = DEFAULT_MIN_BUCKET,
                  multiple_of: int = 1) -> List[int]:
    """The distinct buckets covering request sizes 1..max_rows."""
    ladder = []
    n = 1
    while True:
        b = bucket_for(n, min_bucket, multiple_of)
        if not ladder or b != ladder[-1]:
            ladder.append(b)
        if b >= max_rows:
            return ladder
        n = b + 1


def convert_bucketed(scores: np.ndarray, convert_fn,
                     min_bucket: int = DEFAULT_MIN_BUCKET) -> np.ndarray:
    """Apply an objective's output conversion on bucket-padded (K, N)
    raw scores, so its compiled programs are bucket-shaped like the
    traversal's (the un-jitted jnp ops inside ``convert_output`` would
    otherwise compile per exact N — the same silent per-shape compile
    the traversal bucketing exists to kill).  Conversions are column-
    local (elementwise sigmoid, per-column softmax), so zero-padded
    columns never influence real columns and are stripped on return."""
    import jax.numpy as jnp

    scores = np.asarray(scores, np.float64)
    n = scores.shape[1]
    b = bucket_for(n, min_bucket)
    if b != n:
        scores = np.pad(scores, ((0, 0), (0, b - n)))
    return np.asarray(convert_fn(jnp.asarray(scores)), np.float64)[:, :n]


class BucketedRawPredictor:
    """Raw-score predictor over per-class stacked tree arrays with
    bucket-padded batches.  ``predict_raw_scores`` mirrors
    ``GBDT.predict_raw_scores``'s (K, N) float64 contract."""

    def __init__(self, class_arrays: List[tuple], min_bucket: int = DEFAULT_MIN_BUCKET,
                 shard: bool = False):
        import jax
        import jax.numpy as jnp

        self.num_class_arrays = len(class_arrays)
        self.min_bucket = int(min_bucket)
        self._sharding = None
        self._row_multiple = 1
        if shard:
            devs = jax.local_devices()
            if len(devs) > 1:
                from jax.sharding import NamedSharding, PartitionSpec as P

                from ..parallel import make_mesh

                mesh = make_mesh()
                self._sharding = NamedSharding(mesh, P("data"))
                self._replicated = NamedSharding(mesh, P())
                self._row_multiple = len(devs)
                class_arrays = [
                    tuple(jax.device_put(a, self._replicated) for a in args)
                    for args in class_arrays
                ]
        self.class_arrays = [
            tuple(jnp.asarray(a) for a in args) for args in class_arrays
        ]

    # -- construction --------------------------------------------------
    @classmethod
    def from_tree_arrays(cls, arrays: TreeArrays, num_tree_per_iteration: int,
                         **kw) -> "BucketedRawPredictor":
        """Split the (T, ...) stacked arrays into per-class tuples
        (class of tree i is i % k, matching GBDT.predict_raw_scores).
        Arrays are padded to canonical tree-shape buckets first, so the
        compiled programs are shared across models of the same shape
        class (see ``tree_shape_bucket``)."""
        arrays.validate()
        arrays = pad_tree_arrays(arrays)
        t = arrays.split_feature.shape[0]
        k = int(num_tree_per_iteration)
        if k <= 0 or t % k != 0:
            Log.fatal("%d stacked trees are not a multiple of "
                      "num_tree_per_iteration=%d", t, k)
        class_arrays = []
        for kk in range(k):
            idx = np.arange(kk, t, k)
            class_arrays.append(tuple(
                np.asarray(getattr(arrays, f))[idx] for f in _TREE_ARG_FIELDS
            ))
        return cls(class_arrays, **kw)

    @classmethod
    def from_models(cls, models: List, num_tree_per_iteration: int,
                    **kw) -> "BucketedRawPredictor":
        from .artifact import stacked_tree_arrays

        return cls.from_tree_arrays(
            stacked_tree_arrays(models), num_tree_per_iteration, **kw
        )

    # -- predict -------------------------------------------------------
    def bucket(self, n: int) -> int:
        return bucket_for(n, self.min_bucket, self._row_multiple)

    def _data_planes(self, data: np.ndarray, bucket: int):
        """Triple-float planes of ``data`` padded to ``bucket`` rows."""
        import jax
        import jax.numpy as jnp

        from ..model.ensemble import split_hi_lo

        hi, lo, lo2 = split_hi_lo(np.asarray(data, np.float64))
        pad = bucket - data.shape[0]
        if pad:
            hi = np.pad(hi, ((0, pad), (0, 0)))
            lo = np.pad(lo, ((0, pad), (0, 0)))
            lo2 = np.pad(lo2, ((0, pad), (0, 0)))
        planes = (jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(lo2))
        if self._sharding is not None:
            planes = tuple(jax.device_put(p, self._sharding) for p in planes)
        return planes

    def predict_raw_scores(self, data: np.ndarray) -> np.ndarray:
        """(K, N) float64 raw scores for (N, F) raw features."""
        n = data.shape[0]
        bucket = self.bucket(n)
        planes = self._data_planes(data, bucket)
        fn = _watch()
        out = np.empty((self.num_class_arrays, n))
        for kk, args in enumerate(self.class_arrays):
            out[kk] = np.asarray(fn(*planes, *args), np.float64)[:n]
        tracer.counter("serve_predict_rows", float(n))
        return out

    # -- warmup --------------------------------------------------------
    def warmup(self, max_rows: int, num_features: int,
               buckets: Optional[List[int]] = None) -> Dict:
        """Precompile the bucket ladder up to ``max_rows`` rows.  Returns
        (and traces) the buckets touched and the compile count — after
        this, any request of size <= max(buckets) must hit the cache."""
        if buckets is None:
            buckets = bucket_ladder(max_rows, self.min_bucket, self._row_multiple)
        c0 = compilewatch.total_compiles()
        t0 = time.perf_counter()
        with tracer.span("serve_warmup", buckets=len(buckets)):
            for b in buckets:
                self.predict_raw_scores(np.zeros((b, num_features)))
        stats = {
            "buckets": list(buckets),
            "compiles": compilewatch.total_compiles() - c0,
            "secs": round(time.perf_counter() - t0, 4),
        }
        tracer.event("serve_warmup_done", **stats)
        return stats


class BucketedLinearRawPredictor(BucketedRawPredictor):
    """Linear-leaf (v3 artifact) counterpart of
    ``BucketedRawPredictor``: identical bucket-padded batching and
    (K, N) float64 contract, traversing with
    ``ops/predict.predict_raw_linear`` under the shared
    "serve.predict_linear" watch.  Same-shape-class models (including
    the coefficient K axis, ``pad_linear_tree_arrays``) share every XLA
    program, so a hot swap to a same-shape linear retrain costs zero new
    compiles."""

    @classmethod
    def from_tree_arrays(cls, arrays: LinearTreeArrays,
                         num_tree_per_iteration: int,
                         **kw) -> "BucketedLinearRawPredictor":
        arrays.validate()
        arrays = pad_linear_tree_arrays(arrays)
        t = arrays.split_feature.shape[0]
        k = int(num_tree_per_iteration)
        if k <= 0 or t % k != 0:
            Log.fatal("%d stacked trees are not a multiple of "
                      "num_tree_per_iteration=%d", t, k)
        class_arrays = []
        for kk in range(k):
            idx = np.arange(kk, t, k)
            class_arrays.append(tuple(
                np.asarray(getattr(arrays, f))[idx]
                for f in _LINEAR_TREE_ARG_FIELDS
            ))
        return cls(class_arrays, **kw)

    def predict_raw_scores(self, data: np.ndarray) -> np.ndarray:
        """(K, N) float64 raw scores for (N, F) raw features."""
        n = data.shape[0]
        bucket = self.bucket(n)
        planes = self._data_planes(data, bucket)
        fn = _lwatch()
        out = np.empty((self.num_class_arrays, n))
        for kk, args in enumerate(self.class_arrays):
            out[kk] = np.asarray(fn(*planes, *args), np.float64)[:n]
        tracer.counter("serve_linear_rows", float(n))
        return out


class BucketedQuantizedPredictor:
    """Quantized counterpart of ``BucketedRawPredictor``: the same
    bucket-padded batching and (K, N) float64 contract, but requests are
    rank-encoded on the host (``ops/qpredict.quantize_data``) and
    traversed with one int16 compare per node under the shared
    "serve.qpredict" watch.  Same-shape-class models share every XLA
    program (``pad_qtree_arrays``)."""

    def __init__(self, class_arrays: List[tuple], qbin_edges, qbin_offsets,
                 feature_flags, levels: int,
                 min_bucket: int = DEFAULT_MIN_BUCKET, shard: bool = False):
        import jax
        import jax.numpy as jnp

        self.num_class_arrays = len(class_arrays)
        self.min_bucket = int(min_bucket)
        self.levels = int(levels)
        self._edges = np.asarray(qbin_edges, np.float64)
        self._offsets = np.asarray(qbin_offsets, np.int64)
        self._feature_flags = np.asarray(feature_flags)
        self._sharding = None
        self._row_multiple = 1
        if shard:
            devs = jax.local_devices()
            if len(devs) > 1:
                from jax.sharding import NamedSharding, PartitionSpec as P

                from ..parallel import make_mesh

                mesh = make_mesh()
                self._sharding = NamedSharding(mesh, P("data"))
                self._replicated = NamedSharding(mesh, P())
                self._row_multiple = len(devs)
                class_arrays = [
                    tuple(jax.device_put(a, self._replicated) for a in args)
                    for args in class_arrays
                ]
        self.class_arrays = [
            tuple(jnp.asarray(a) for a in args) for args in class_arrays
        ]

    # -- construction --------------------------------------------------
    @classmethod
    def from_qtree_arrays(cls, arrays, num_tree_per_iteration: int,
                          **kw) -> "BucketedQuantizedPredictor":
        arrays.validate()
        arrays = pad_qtree_arrays(arrays)
        t = arrays.split_feature.shape[0]
        k = int(num_tree_per_iteration)
        if k <= 0 or t % k != 0:
            Log.fatal("%d stacked trees are not a multiple of "
                      "num_tree_per_iteration=%d", t, k)
        class_arrays = []
        for kk in range(k):
            idx = np.arange(kk, t, k)
            class_arrays.append(tuple(
                np.asarray(getattr(arrays, f))[idx]
                for f in _Q_TREE_ARG_FIELDS
            ))
        return cls(class_arrays, arrays.qbin_edges, arrays.qbin_offsets,
                   arrays.feature_flags, arrays.levels, **kw)

    # -- predict -------------------------------------------------------
    def bucket(self, n: int) -> int:
        return bucket_for(n, self.min_bucket, self._row_multiple)

    def _qbins(self, data: np.ndarray, bucket: int):
        """Host rank-encode ``data`` and pad to ``bucket`` rows (padding
        rows are all-zero codes; traversal is row-independent and the
        padding is stripped on return)."""
        import jax
        import jax.numpy as jnp

        from ..ops.qpredict import quantize_data

        qb = quantize_data(np.asarray(data, np.float64), self._edges,
                           self._offsets, self._feature_flags)
        pad = bucket - qb.shape[0]
        if pad:
            qb = np.pad(qb, ((0, pad), (0, 0)))
        qb = jnp.asarray(qb)
        if self._sharding is not None:
            qb = jax.device_put(qb, self._sharding)
        return qb

    def predict_raw_scores(self, data: np.ndarray) -> np.ndarray:
        """(K, N) float64 raw scores for (N, F) raw features."""
        n = data.shape[0]
        bucket = self.bucket(n)
        qb = self._qbins(data, bucket)
        fn = _qwatch()
        out = np.empty((self.num_class_arrays, n))
        for kk, args in enumerate(self.class_arrays):
            out[kk] = np.asarray(
                fn(qb, *args, levels=self.levels), np.float64)[:n]
        tracer.counter("serve_qpredict_rows", float(n))
        return out

    # -- warmup --------------------------------------------------------
    def warmup(self, max_rows: int, num_features: int,
               buckets: Optional[List[int]] = None) -> Dict:
        """Precompile the bucket ladder up to ``max_rows`` rows (see
        ``BucketedRawPredictor.warmup``)."""
        if buckets is None:
            buckets = bucket_ladder(max_rows, self.min_bucket, self._row_multiple)
        c0 = compilewatch.total_compiles()
        t0 = time.perf_counter()
        with tracer.span("serve_warmup", buckets=len(buckets)):
            for b in buckets:
                self.predict_raw_scores(np.zeros((b, num_features)))
        stats = {
            "buckets": list(buckets),
            "compiles": compilewatch.total_compiles() - c0,
            "secs": round(time.perf_counter() - t0, 4),
        }
        tracer.event("serve_warmup_done", **stats)
        return stats
