"""Latency-outlier circuit breakers for the serving fleet.

The proxy's health prober only sees *crash* failures: a hung replica
still answers ``/readyz`` so it keeps getting picked and holds every
routed request for the full backend socket timeout.  This module closes
that gap with the same EWMA-vs-fleet-median shape the elastic runtime
uses for straggler detection (``parallel/shardplan.py``): each backend
carries a latency EWMA, an observation is **hot** when it failed outright
or when the backend's EWMA exceeds ``k``× the fleet-median EWMA, and
``m`` consecutive hot observations trip the breaker

    CLOSED ──m hot──▶ OPEN ──open_s cooldown──▶ HALF_OPEN ──trial ok──▶ CLOSED
                        ▲                            │trial bad
                        └────────────────────────────┘

HALF_OPEN admits exactly one in-flight trial request (claimed under the
proxy's pick lock via :meth:`begin_attempt`); a good trial closes the
breaker, a bad one re-opens it for another cooldown.  The breaker only
*advises* the proxy's pick — when every backend is open the proxy falls
back to any healthy backend, so breakers can never zero out availability.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class _Entry:
    __slots__ = ("ewma", "hot", "state", "opened_at", "trial_inflight",
                 "opens", "observations")

    def __init__(self):
        self.ewma = 0.0
        self.hot = 0
        self.state = CLOSED
        self.opened_at = 0.0
        self.trial_inflight = False
        self.opens = 0
        self.observations = 0


class LatencyBreaker:
    """Per-backend CLOSED→OPEN→HALF_OPEN breaker keyed by address."""

    def __init__(self, k: float = 3.0, m: int = 5, open_s: float = 2.0,
                 alpha: float = 0.3):
        self.k = float(k)
        self.m = max(1, int(m))
        self.open_s = float(open_s)
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}

    def _entry(self, addr: str) -> _Entry:
        e = self._entries.get(addr)
        if e is None:
            e = self._entries[addr] = _Entry()
        return e

    def _median_ewma(self) -> float:
        vals = sorted(e.ewma for e in self._entries.values()
                      if e.observations > 0)
        if not vals:
            return 0.0
        mid = len(vals) // 2
        if len(vals) % 2:
            return vals[mid]
        return 0.5 * (vals[mid - 1] + vals[mid])

    # -- pick-side ------------------------------------------------------
    def state(self, addr: str) -> str:
        with self._lock:
            e = self._entries.get(addr)
            return e.state if e is not None else CLOSED

    def trial_eligible(self, addr: str) -> bool:
        """True when ``addr`` is due its single half-open probe: OPEN
        past the cooldown, or HALF_OPEN with no trial in flight."""
        with self._lock:
            e = self._entries.get(addr)
            if e is None:
                return False
            if e.state == OPEN:
                return (time.monotonic() - e.opened_at) >= self.open_s
            if e.state == HALF_OPEN:
                return not e.trial_inflight
            return False

    def begin_attempt(self, addr: str) -> None:
        """Called under the proxy's pick for the chosen backend: claims
        the half-open trial slot so concurrent picks can't double-probe."""
        with self._lock:
            e = self._entries.get(addr)
            if e is None:
                return
            if e.state == OPEN and \
                    (time.monotonic() - e.opened_at) >= self.open_s:
                e.state = HALF_OPEN
                e.trial_inflight = True
            elif e.state == HALF_OPEN and not e.trial_inflight:
                e.trial_inflight = True

    # -- observe-side ---------------------------------------------------
    def observe(self, addr: str, elapsed_s: float,
                ok: bool) -> Optional[str]:
        """Record one attempt's outcome.  Returns the transition it
        caused (``"open"``/``"close"``/``"reopen"``) or None."""
        now = time.monotonic()
        with self._lock:
            e = self._entry(addr)
            e.observations += 1
            e.ewma = (self.alpha * float(elapsed_s)
                      + (1.0 - self.alpha) * e.ewma) \
                if e.observations > 1 else float(elapsed_s)
            med = self._median_ewma()
            outlier = (not ok) or (med > 0.0 and e.ewma > self.k * med)
            if e.state == HALF_OPEN:
                # the trial verdict (a late pre-open result lands here
                # too — acceptable: it is still fresh evidence).  Judged
                # on the PROBE's own outcome, not the EWMA: the EWMA is
                # still poisoned by the open-causing latencies and would
                # take ~1/alpha probes to decay below k×median
                e.trial_inflight = False
                if (not ok) or (med > 0.0
                                and float(elapsed_s) > self.k * med):
                    e.state = OPEN
                    e.opened_at = now
                    e.opens += 1
                    e.hot = self.m
                    return "reopen"
                e.state = CLOSED
                e.hot = 0
                e.ewma = float(elapsed_s)  # re-enter with fresh stats
                return "close"
            if outlier:
                e.hot += 1
            else:
                e.hot = 0
            if e.state == CLOSED and e.hot >= self.m:
                e.state = OPEN
                e.opened_at = now
                e.opens += 1
                e.trial_inflight = False
                return "open"
        return None

    # -- ops surface ----------------------------------------------------
    def open_count(self) -> int:
        with self._lock:
            return sum(1 for e in self._entries.values()
                       if e.state != CLOSED)

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            return {
                addr: {
                    "state": e.state,
                    "ewma_ms": round(1e3 * e.ewma, 3),
                    "hot": int(e.hot),
                    "opens": int(e.opens),
                    "observations": int(e.observations),
                }
                for addr, e in self._entries.items()
            }
