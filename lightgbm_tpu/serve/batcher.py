"""Microbatching request engine.

TPU traversal throughput comes from batch size: a single-row dispatch
pays the same dispatch + program overhead as a 1024-row one.  The
batcher makes concurrent single/small requests share that cost: callers
block in ``submit()`` while a background thread coalesces queued
requests into one device batch, bounded by ``max_batch_size`` rows and
``max_delay_ms`` of added latency for the request at the head of the
queue.

Overload policy is shed-not-queue: the pending-row budget is a hard
bound, and a ``submit()`` that would exceed it raises
``ServerOverloaded`` immediately instead of stretching everyone's
latency (the caller sees a 503 and can retry against another replica).
Requests whose caller deadline expires while still queued are dropped
before they waste device time.

Metrics (queue depth, batch occupancy, shed/timeout counts, latency
quantiles) are kept in-process for ``stats()``, mirrored to the obs
tracer when tracing is enabled, and — always — observed into the
Prometheus registry (obs/metrics.py) that ``GET /metrics`` scrapes:
request/row/batch/shed/deadline counters, batch-size and latency
histograms, and the queue-depth gauge.  Registry updates are plain
locked float adds, negligible next to a device dispatch.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..obs import metrics, tracer
from ..utils.log import Log

# shared across batcher instances (a server runs two — converted and
# raw-score — and Prometheus wants the aggregate; per-batcher detail
# stays on /stats)
_M_REQUESTS = metrics.registry.counter(
    "lightgbm_tpu_serve_requests_total", "predict requests submitted")
_M_ROWS = metrics.registry.counter(
    "lightgbm_tpu_serve_rows_total", "predict rows submitted")
_M_BATCHES = metrics.registry.counter(
    "lightgbm_tpu_serve_batches_total", "device batches executed")
_M_SHED = metrics.registry.counter(
    "lightgbm_tpu_serve_shed_total",
    "requests shed by the queue-full overload policy (HTTP 503)")
_M_TIMEOUTS = metrics.registry.counter(
    "lightgbm_tpu_serve_deadline_expired_total",
    "requests dropped because their deadline expired while queued (504)")
_M_ERRORS = metrics.registry.counter(
    "lightgbm_tpu_serve_errors_total", "device batches that raised")
_M_QUEUE = metrics.registry.gauge(
    "lightgbm_tpu_serve_queue_rows", "rows currently queued")
_M_BATCH_ROWS = metrics.registry.histogram(
    "lightgbm_tpu_serve_batch_rows", "rows per executed device batch",
    buckets=metrics.BATCH_BUCKETS)
_M_LATENCY = metrics.registry.histogram(
    "lightgbm_tpu_serve_latency_seconds",
    "request latency, enqueue to completed batch",
    buckets=metrics.LATENCY_BUCKETS)


class ServerOverloaded(RuntimeError):
    """The pending-row queue is full; the request was shed."""


class RequestTimeout(RuntimeError):
    """The request's deadline expired before a batch picked it up."""


class _Request:
    __slots__ = ("rows", "deadline", "done", "result", "error", "info",
                 "t_enqueue")

    def __init__(self, rows: np.ndarray, deadline: float):
        self.rows = rows
        self.deadline = deadline
        self.done = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.info = None  # batch-level metadata (e.g. model version)
        self.t_enqueue = time.perf_counter()


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


class MicroBatcher:
    """Coalesce concurrent ``submit(rows)`` calls into bounded batches.

    ``predict_fn(batch) -> per-row outputs`` must return an array whose
    leading axis is the batch row axis ((N,) or (N, K)) — exactly the
    ``PackedPredictor.predict`` contract.  It may instead return
    ``(outputs, info)``: the extra ``info`` (a hot-swap predictor's
    model version) is attached to every request of that batch and
    surfaced through ``submit_ex`` — because it is sampled once per
    BATCH, every request is attributable to exactly one model version
    even across a swap boundary.
    """

    def __init__(
        self,
        predict_fn: Callable[[np.ndarray], np.ndarray],
        max_batch_size: int = 1024,
        max_delay_ms: float = 5.0,
        max_queue_rows: int = 8192,
        request_timeout_ms: float = 2000.0,
        latency_window: int = 2048,
    ):
        self.predict_fn = predict_fn
        self.max_batch_size = int(max_batch_size)
        self.max_delay_ms = float(max_delay_ms)
        self.max_queue_rows = int(max_queue_rows)
        self.request_timeout_ms = float(request_timeout_ms)

        self._queue: collections.deque = collections.deque()
        self._queued_rows = 0
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self._draining = False
        self._executing_rows = 0  # rows inside the running predict_fn
        self._counts = {"requests": 0, "rows": 0, "batches": 0,
                        "shed": 0, "timeouts": 0, "errors": 0}
        self._occupancy: collections.deque = collections.deque(maxlen=256)
        self._latency_s: collections.deque = collections.deque(maxlen=latency_window)
        self._thread = threading.Thread(
            target=self._loop, name="lightgbm-tpu-batcher", daemon=True
        )
        self._thread.start()

    # -- client side ---------------------------------------------------
    def submit(self, rows: np.ndarray, timeout_ms: Optional[float] = None) -> np.ndarray:
        """Block until the batch containing ``rows`` completes; returns
        the per-row outputs for exactly these rows.  Raises
        ``ServerOverloaded`` (queue full), ``RequestTimeout`` (deadline
        expired before execution), or the predict error."""
        return self._submit(rows, timeout_ms).result

    def submit_ex(self, rows: np.ndarray,
                  timeout_ms: Optional[float] = None):
        """Like ``submit`` but returns ``(outputs, info)`` where
        ``info`` is whatever the predict_fn returned alongside the
        outputs for this request's batch (None for plain predict_fns or
        empty requests)."""
        req = self._submit(rows, timeout_ms)
        return req.result, req.info

    def _submit(self, rows: np.ndarray,
                timeout_ms: Optional[float]) -> _Request:
        rows = np.asarray(rows, np.float64)
        if rows.ndim == 1:
            rows = rows.reshape(1, -1)
        tmo = self.request_timeout_ms if timeout_ms is None else float(timeout_ms)
        if tmo <= 0:
            # deadline propagation (docs/ROBUSTNESS.md): a request whose
            # X-Deadline-Ms budget is already spent fails fast — no
            # queue slot, no device work
            with self._lock:
                self._counts["timeouts"] += 1
            _M_TIMEOUTS.inc()
            tracer.counter("serve_request_timeout")
            raise RequestTimeout("deadline exhausted on arrival")
        req = _Request(rows, deadline=time.monotonic() + tmo / 1e3)
        if rows.shape[0] == 0:
            req.result = np.empty((0,))
            return req
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self._draining:
                # drain admits nothing new: queued work finishes, the
                # caller sheds to another replica/model (HTTP 503)
                self._counts["shed"] += 1
                _M_SHED.inc()
                tracer.counter("serve_shed")
                raise ServerOverloaded("batcher is draining")
            if self._queued_rows + rows.shape[0] > self.max_queue_rows:
                self._counts["shed"] += 1
                _M_SHED.inc()
                tracer.counter("serve_shed")
                raise ServerOverloaded(
                    f"queue holds {self._queued_rows} rows; "
                    f"+{rows.shape[0]} exceeds max_queue_rows="
                    f"{self.max_queue_rows}"
                )
            self._counts["requests"] += 1
            self._counts["rows"] += rows.shape[0]
            _M_REQUESTS.inc()
            _M_ROWS.inc(rows.shape[0])
            self._queue.append(req)
            self._queued_rows += rows.shape[0]
            _M_QUEUE.set(self._queued_rows)
            self._wake.notify()
        # wait past the deadline by a grace period: an in-flight batch
        # holding this request may still complete it
        req.done.wait(tmo / 1e3 + 60.0)
        if req.error is not None:
            raise req.error
        if req.result is None:
            raise RequestTimeout("request was never executed")
        lat = time.perf_counter() - req.t_enqueue
        self._latency_s.append(lat)
        _M_LATENCY.observe(lat)
        return req

    # -- batch loop ----------------------------------------------------
    def _take_batch(self) -> List[_Request]:
        """Pop up to max_batch_size rows' worth of requests, waiting at
        most max_delay_ms after the first arrival; expired requests are
        failed here rather than executed."""
        with self._lock:
            while not self._queue and not self._closed:
                self._wake.wait(0.1)
            if self._closed and not self._queue:
                return []
            batch_deadline = time.monotonic() + self.max_delay_ms / 1e3
            taken: List[_Request] = []
            rows = 0
            while True:
                while self._queue:
                    req = self._queue[0]
                    if time.monotonic() > req.deadline:
                        self._queue.popleft()
                        self._queued_rows -= req.rows.shape[0]
                        self._counts["timeouts"] += 1
                        _M_TIMEOUTS.inc()
                        tracer.counter("serve_request_timeout")
                        req.error = RequestTimeout(
                            "deadline expired while queued")
                        req.done.set()
                        continue
                    if rows and rows + req.rows.shape[0] > self.max_batch_size:
                        return taken
                    self._queue.popleft()
                    self._queued_rows -= req.rows.shape[0]
                    taken.append(req)
                    rows += req.rows.shape[0]
                    if rows >= self.max_batch_size:
                        return taken
                remaining = batch_deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    return taken
                self._wake.wait(remaining)

    def _loop(self) -> None:
        while True:
            taken = self._take_batch()
            if not taken:
                if self._closed:
                    return
                continue
            batch = (taken[0].rows if len(taken) == 1
                     else np.concatenate([r.rows for r in taken], axis=0))
            self._occupancy.append(batch.shape[0])
            with self._lock:
                self._executing_rows = batch.shape[0]
            _M_QUEUE.set(self._queued_rows)
            _M_BATCH_ROWS.observe(batch.shape[0])
            tracer.gauge("serve_queue_depth", float(self._queued_rows))
            tracer.gauge("serve_batch_rows", float(batch.shape[0]))
            try:
                with tracer.span("serve_batch", rows=batch.shape[0],
                                 requests=len(taken)):
                    out = self.predict_fn(batch)
                self._counts["batches"] += 1
                _M_BATCHES.inc()
            except BaseException as e:  # predict failure fans out to callers
                self._counts["errors"] += 1
                _M_ERRORS.inc()
                for req in taken:
                    req.error = e
                    req.done.set()
                with self._lock:
                    self._executing_rows = 0
                    self._wake.notify_all()
                continue
            # a predict_fn may return (outputs, info): the info —
            # sampled once per batch — stamps every request with the
            # single model version that produced its rows
            info = None
            if isinstance(out, tuple):
                out, info = out
            start = 0
            for req in taken:
                n = req.rows.shape[0]
                req.result = np.asarray(out[start:start + n])
                req.info = info
                start += n
                req.done.set()
            with self._lock:
                self._executing_rows = 0
                self._wake.notify_all()

    # -- ops surface ---------------------------------------------------
    def stats(self) -> Dict:
        with self._lock:
            counts = dict(self._counts)
            depth = self._queued_rows
            executing = self._executing_rows
            draining = self._draining
        lat = sorted(self._latency_s)
        occ = list(self._occupancy)
        return {
            **counts,
            "queue_rows": depth,
            "inflight_rows": depth + executing,
            "draining": draining,
            "batch_occupancy_mean": round(float(np.mean(occ)), 2) if occ else 0.0,
            "latency_p50_ms": round(1e3 * _quantile(lat, 0.50), 3),
            "latency_p99_ms": round(1e3 * _quantile(lat, 0.99), 3),
        }

    def drain(self, timeout_s: float = 10.0) -> bool:
        """In-process drain (hot-swap uses this mid-life, not only at
        exit): stop admitting new submits (they shed with
        ``ServerOverloaded``), let everything queued and executing
        finish, then settle the accounting — ``inflight_rows`` and
        ``draining`` both read a stable ZERO after a completed drain.
        Returns True when nothing was left in flight at the deadline."""
        deadline = time.monotonic() + float(timeout_s)
        with self._lock:
            self._draining = True
            self._wake.notify_all()
            while self._queued_rows > 0 or self._executing_rows > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._wake.wait(min(remaining, 0.1))
            drained = self._queued_rows == 0 and self._executing_rows == 0
            # a COMPLETED drain settles to zero: not draining anymore,
            # nothing in flight (the gauges-readable steady state)
            if drained:
                self._draining = False
        if not drained:
            Log.warning("batcher drain timed out with %d queued + %d "
                        "executing rows", self._queued_rows,
                        self._executing_rows)
        return drained

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._wake.notify_all()
        self._thread.join(timeout=5.0)
