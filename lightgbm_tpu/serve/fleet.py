"""Serving fleet: zero-downtime hot swap + replicated front end.

Two pieces, both riding on the existing serve/ layers:

``SwappablePredictor`` — the hot-swap slot.  One replica process holds
exactly one slot; the microbatchers' predict_fn samples the slot's
``(version, PackedPredictor)`` pointer ONCE per device batch, so every
batch — and therefore every request — is served by exactly one model
version even while a swap lands.  ``swap_to`` loads and ``warmup()``s
the incoming artifact in the calling (background) thread while traffic
keeps flowing on the old model, flips the pointer at a microbatch
boundary, then waits for the old version's in-flight batches to drain.
Because the compile cache is keyed on tree SHAPE, not model identity
(serve/compilecache.tree_shape_bucket), a retrain with the same
``num_trees/num_leaves`` inherits every warm XLA program: the swap
compiles NOTHING (pinned by tests/test_fleet.py).

``FleetProxy`` — a tiny stdlib-HTTP load-balancing front end over N
replica processes: round-robin or least-loaded backend choice,
per-replica health ejection (a dead or connection-refusing backend is
ejected and retried elsewhere within the same request — predict is
idempotent, so a SIGKILLed replica mid-request costs a retry, never a
dropped response), and a background ``/readyz`` prober that restores
recovered backends.  ``python -m lightgbm_tpu fleet`` spawns N
``serve`` subprocesses on a shared model registry plus the proxy.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import compilewatch, tracer
from ..obs.metrics import LATENCY_BUCKETS, registry as metrics_registry
from ..utils.log import Log
from .artifact import PackedPredictor, PredictorArtifact

_M_SWAPS = metrics_registry.counter(
    "lightgbm_tpu_serve_model_swaps_total",
    "completed hot swaps to a new model version")
_M_SWAP_SECONDS = metrics_registry.histogram(
    "lightgbm_tpu_serve_swap_seconds",
    "hot-swap latency: artifact load + warmup to traffic on the new model",
    buckets=LATENCY_BUCKETS)
_M_SWAP_COMPILES = metrics_registry.counter(
    "lightgbm_tpu_serve_swap_compiles_total",
    "XLA compiles attributable to hot swaps (0 for same-shape retrains)")
_M_PROXY_REQS = metrics_registry.counter(
    "lightgbm_tpu_proxy_requests_total", "requests handled by the proxy")
_M_PROXY_RETRIES = metrics_registry.counter(
    "lightgbm_tpu_proxy_retries_total",
    "request attempts re-routed to another backend")
_M_PROXY_EJECTIONS = metrics_registry.counter(
    "lightgbm_tpu_proxy_ejections_total",
    "backends ejected after a connection failure")
_M_PROXY_LATENCY = metrics_registry.histogram(
    "lightgbm_tpu_proxy_latency_seconds",
    "proxy request latency including retries", buckets=LATENCY_BUCKETS)
_M_PROXY_CANARY = metrics_registry.counter(
    "lightgbm_tpu_proxy_canary_requests_total",
    "predict requests answered by the canary backend")


# ----------------------------------------------------------------------
# hot-swap slot
# ----------------------------------------------------------------------
class SwappablePredictor:
    """Version-stamped predictor slot with zero-downtime swap.

    ``predict`` returns ``(outputs, version)``: the MicroBatcher calls
    it once per device batch, so the version is sampled exactly once per
    batch — the concurrent-swap attribution contract."""

    def __init__(self, predictor: PackedPredictor, version: int = 1):
        self._lock = threading.Lock()
        self._drain_cv = threading.Condition(self._lock)
        self._current: Tuple[int, PackedPredictor] = (int(version), predictor)
        self._inflight: Dict[int, int] = {}
        self._swaps = 0
        self.last_swap: Dict = {}
        metrics_registry.gauge(
            "lightgbm_tpu_serve_model_version",
            "model version currently receiving traffic",
            fn=lambda: float(self.version))
        metrics_registry.gauge(
            "lightgbm_tpu_serve_draining_model_versions",
            "old model versions still finishing in-flight batches",
            fn=lambda: float(self.draining_versions))

    # -- introspection -------------------------------------------------
    @property
    def version(self) -> int:
        return self._current[0]

    @property
    def predictor(self) -> PackedPredictor:
        return self._current[1]

    @property
    def artifact(self) -> PredictorArtifact:
        return self._current[1].artifact

    @property
    def num_features(self) -> int:
        return self._current[1].num_features

    @property
    def swaps(self) -> int:
        return self._swaps

    @property
    def draining_versions(self) -> int:
        with self._lock:
            cur = self._current[0]
            return sum(1 for v, n in self._inflight.items()
                       if v != cur and n > 0)

    # -- serving path --------------------------------------------------
    def predict(self, batch: np.ndarray, raw_score: bool = False):
        """(outputs, version) — the whole batch runs on ONE model."""
        with self._lock:
            ver, pred = self._current
            self._inflight[ver] = self._inflight.get(ver, 0) + 1
        try:
            out = pred.predict(batch, raw_score=raw_score)
        finally:
            with self._drain_cv:
                self._inflight[ver] -= 1
                if self._inflight[ver] <= 0:
                    self._inflight.pop(ver, None)
                    self._drain_cv.notify_all()
        return out, ver

    def warmup(self, max_rows: int) -> Dict:
        return self._current[1].warmup(max_rows)

    # -- swap ----------------------------------------------------------
    def swap_to(self, artifact: PredictorArtifact, version: int,
                warmup_max_rows: int = 4096, do_warmup: bool = True,
                drain_timeout_s: float = 30.0) -> Dict:
        """Zero-downtime swap: build + warm the new predictor while the
        old one keeps serving, flip the pointer (the next microbatch
        runs on the new model), then wait for the old version's
        in-flight batches to drain.  Returns swap stats including the
        compile count the swap cost (0 for a same-shape retrain)."""
        t0 = time.perf_counter()
        c0 = compilewatch.total_compiles()
        new_pred = PackedPredictor(artifact)
        if do_warmup:
            new_pred.warmup(warmup_max_rows)
        with self._lock:
            old_ver, _old_pred = self._current
            self._current = (int(version), new_pred)
            self._swaps += 1
        swap_s = time.perf_counter() - t0
        new_compiles = compilewatch.total_compiles() - c0
        drained = self._wait_version_drained(old_ver, drain_timeout_s)
        stats = {
            "from_version": int(old_ver),
            "to_version": int(version),
            "swap_ms": round(1e3 * swap_s, 3),
            "new_compiles": int(new_compiles),
            "old_drained": bool(drained),
        }
        self.last_swap = stats
        _M_SWAPS.inc()
        _M_SWAP_SECONDS.observe(swap_s)
        if new_compiles > 0:
            _M_SWAP_COMPILES.inc(new_compiles)
        tracer.event("serve.swap", **stats)
        Log.info("serve: hot-swapped model v%d -> v%d in %.1f ms "
                 "(%d new compiles, old %s)", old_ver, version,
                 stats["swap_ms"], new_compiles,
                 "drained" if drained else "DRAIN TIMED OUT")
        return stats

    def _wait_version_drained(self, version: int, timeout_s: float) -> bool:
        deadline = time.monotonic() + float(timeout_s)
        with self._drain_cv:
            while self._inflight.get(version, 0) > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._drain_cv.wait(min(remaining, 0.1))
        return True


# ----------------------------------------------------------------------
# load-balancing proxy
# ----------------------------------------------------------------------
class _Backend:
    __slots__ = ("host", "port", "healthy", "inflight", "requests",
                 "failures", "ejections")

    def __init__(self, addr: str):
        host, _, port = addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.healthy = True
        self.inflight = 0
        self.requests = 0
        self.failures = 0
        self.ejections = 0

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def as_dict(self) -> Dict:
        return {"addr": self.addr, "healthy": self.healthy,
                "inflight": self.inflight, "requests": self.requests,
                "failures": self.failures, "ejections": self.ejections}


class FleetProxy(ThreadingHTTPServer):
    """Round-robin / least-loaded HTTP proxy with health ejection.

    Local endpoints: ``/healthz`` (proxy liveness), ``/fleet/stats``
    (per-backend health + counters), ``/metrics`` (Prometheus).
    Everything else is forwarded to a healthy backend; connection
    failures eject the backend and the request retries elsewhere until
    ``retry_deadline_s`` — a response is dropped only when NO backend
    answers for that long."""

    daemon_threads = True

    def __init__(self, addr, backends: List[str], policy: str = "least_loaded",
                 backend_timeout_s: float = 30.0, health_poll_s: float = 0.5,
                 retry_deadline_s: float = 10.0):
        if not backends:
            Log.fatal("fleet proxy needs at least one backend")
        if policy not in ("least_loaded", "rr"):
            Log.fatal("unknown proxy policy %r (least_loaded or rr)", policy)
        self.backends = [_Backend(b) for b in backends]
        self.policy = policy
        self.backend_timeout_s = float(backend_timeout_s)
        self.health_poll_s = float(health_poll_s)
        self.retry_deadline_s = float(retry_deadline_s)
        self._block = threading.Lock()
        self._rr = 0
        self._stop = threading.Event()
        self.t_start = time.time()
        # canary slice (docs/FACTORY.md): an out-of-rotation backend
        # pinned to the candidate version; a deterministic fraction of
        # /predict traffic is diverted to it, and a canary failure falls
        # back into the main pool so the client never pays for it
        self.canary: Optional[_Backend] = None
        self.canary_fraction = 0.0
        self._canary_tick = 0
        metrics_registry.gauge(
            "lightgbm_tpu_proxy_healthy_backends",
            "backends currently accepting traffic",
            fn=lambda: float(sum(1 for b in self.backends if b.healthy)))
        self._health_thread = threading.Thread(
            target=self._health_loop, name="ltpu-fleet-health", daemon=True)
        super().__init__(addr, _ProxyHandler)
        self._health_thread.start()

    # -- backend choice ------------------------------------------------
    def pick(self, exclude: Optional[set] = None) -> Optional[_Backend]:
        exclude = exclude or set()
        with self._block:
            candidates = [b for b in self.backends
                          if b.healthy and b.addr not in exclude]
            if not candidates:
                # all excluded this attempt round: fall back to any
                # healthy backend (it may have recovered)
                candidates = [b for b in self.backends if b.healthy]
            if not candidates:
                return None
            self._rr += 1
            if self.policy == "rr":
                chosen = candidates[self._rr % len(candidates)]
            else:
                # least-loaded, with a rotating tie-break so idle fleets
                # still spread sequential traffic instead of hammering
                # the first backend
                lo = min(b.inflight for b in candidates)
                tied = [b for b in candidates if b.inflight == lo]
                chosen = tied[self._rr % len(tied)]
            chosen.inflight += 1
            chosen.requests += 1
            return chosen

    # -- canary slice --------------------------------------------------
    def set_canary(self, addr: Optional[str],
                   fraction: float = 0.0) -> None:
        """Install (or clear with ``addr=None``/``fraction<=0``) the
        canary backend receiving ``fraction`` of /predict traffic."""
        with self._block:
            if addr and fraction > 0:
                self.canary = _Backend(addr)
                self.canary_fraction = min(1.0, float(fraction))
                self._canary_tick = 0
            else:
                self.canary = None
                self.canary_fraction = 0.0
        tracer.event("fleet.canary",
                     addr=str(addr) if addr and fraction > 0 else None,
                     fraction=float(self.canary_fraction))

    def pick_canary(self) -> Optional[_Backend]:
        """Deterministic fraction routing: predict request t diverts to
        the canary exactly when ``floor(t*f)`` advances — fraction f of
        traffic with no RNG and no burst (every 1/f-th request)."""
        with self._block:
            c = self.canary
            if c is None or not c.healthy:
                return None
            self._canary_tick += 1
            t, f = self._canary_tick, self.canary_fraction
            if int(t * f) <= int((t - 1) * f):
                return None
            c.inflight += 1
            c.requests += 1
            return c

    def release(self, backend: _Backend) -> None:
        with self._block:
            backend.inflight = max(0, backend.inflight - 1)

    def eject(self, backend: _Backend) -> None:
        with self._block:
            backend.failures += 1
            if backend.healthy:
                backend.healthy = False
                backend.ejections += 1
                _M_PROXY_EJECTIONS.inc()
                Log.warning("fleet: ejected backend %s after a "
                            "connection failure", backend.addr)

    # -- health probing ------------------------------------------------
    def _probe(self, backend: _Backend) -> bool:
        try:
            conn = http.client.HTTPConnection(backend.host, backend.port,
                                              timeout=2.0)
            try:
                conn.request("GET", "/readyz")
                return conn.getresponse().status == 200
            finally:
                conn.close()
        except OSError:
            return False
        except http.client.HTTPException:
            return False

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_poll_s):
            with self._block:
                c = self.canary
            probed = list(self.backends) + ([c] if c is not None else [])
            for b in probed:
                ok = self._probe(b)
                with self._block:
                    if ok and not b.healthy:
                        Log.info("fleet: backend %s recovered", b.addr)
                    b.healthy = ok

    # -- ops surface ---------------------------------------------------
    def stats(self) -> Dict:
        with self._block:
            backends = [b.as_dict() for b in self.backends]
            canary = (dict(self.canary.as_dict(),
                           fraction=self.canary_fraction)
                      if self.canary is not None else None)
        return {
            "uptime_s": round(time.time() - self.t_start, 1),
            "policy": self.policy,
            "healthy": sum(1 for b in backends if b["healthy"]),
            "backends": backends,
            "canary": canary,
        }

    def shutdown(self):
        self._stop.set()
        super().shutdown()


class _ProxyHandler(BaseHTTPRequestHandler):
    server_version = "lightgbm-tpu-fleet/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        Log.debug("fleet: " + fmt, *args)

    def _reply(self, code: int, payload: bytes,
               headers: Optional[List[Tuple[str, str]]] = None) -> None:
        self.send_response(code)
        sent = set()
        for k, v in headers or []:
            if k.lower() in ("content-type", "x-model-version"):
                self.send_header(k, v)
                sent.add(k.lower())
        if "content-type" not in sent:
            self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _reply_json(self, code: int, obj) -> None:
        self._reply(code, (json.dumps(obj) + "\n").encode())

    def do_GET(self):
        if self.path == "/healthz":
            self._reply_json(200, {"status": "ok", "role": "proxy"})
        elif self.path == "/fleet/stats":
            self._reply_json(200, self.server.stats())
        elif self.path == "/metrics":
            self._reply(200, metrics_registry.render().encode(),
                        headers=[("Content-Type",
                                  "text/plain; version=0.0.4; charset=utf-8")])
        else:
            self._forward("GET", body=None)

    def do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        if self.path == "/fleet/canary":
            self._do_canary(body)
            return
        self._forward("POST", body=body)

    def _do_canary(self, body: bytes) -> None:
        """POST /fleet/canary {"addr": "host:port", "fraction": 0.2} —
        install a canary slice; null addr or fraction<=0 clears it."""
        try:
            req = json.loads(body.decode("utf-8") or "{}")
            addr = req.get("addr")
            fraction = float(req.get("fraction") or 0.0)
        except (ValueError, AttributeError) as e:
            self._reply_json(400, {"error": f"bad canary request: {e}"})
            return
        self.server.set_canary(addr, fraction)
        with self.server._block:
            c = self.server.canary
            self._reply_json(200, {
                "canary": c.addr if c is not None else None,
                "fraction": self.server.canary_fraction,
            })

    def _forward(self, method: str, body: Optional[bytes]) -> None:
        """Relay to a healthy backend; eject-and-retry on connection
        failures, re-route 503s (draining/overloaded replica) when
        another backend exists.  Predict requests are idempotent, so a
        retry can never double-apply anything."""
        srv: FleetProxy = self.server
        t0 = time.perf_counter()
        _M_PROXY_REQS.inc()
        deadline = time.monotonic() + srv.retry_deadline_s
        if method == "POST" and self.path.partition("?")[0] == "/predict":
            canary = srv.pick_canary()
            if canary is not None:
                status = None
                try:
                    status, headers, payload = self._try_backend(
                        srv, canary, method, body)
                except (OSError, http.client.HTTPException):
                    pass
                finally:
                    srv.release(canary)
                if status is not None and status < 500 and status != 503:
                    _M_PROXY_CANARY.inc()
                    _M_PROXY_LATENCY.observe(time.perf_counter() - t0)
                    self._reply(status, payload, headers=headers)
                    return
                # a failing canary never costs the client a response:
                # fall back into the main pool.  The canary replica's
                # own per-version error metrics carry the verdict
                # evidence — the proxy only limits the blast radius.
                _M_PROXY_RETRIES.inc()
        tried_this_round: set = set()
        unavailable_503 = 0
        attempt = 0
        while True:
            backend = srv.pick(exclude=tried_this_round)
            if backend is None:
                if time.monotonic() > deadline:
                    self._reply_json(502, {
                        "error": "no healthy backend",
                        "attempts": attempt,
                    })
                    return
                time.sleep(0.05)
                tried_this_round.clear()  # health loop may restore one
                continue
            attempt += 1
            try:
                status, headers, payload = self._try_backend(
                    srv, backend, method, body)
            except (OSError, http.client.HTTPException):
                srv.eject(backend)
                tried_this_round.add(backend.addr)
                _M_PROXY_RETRIES.inc()
                if time.monotonic() > deadline:
                    self._reply_json(502, {
                        "error": "no backend answered before the retry "
                                 "deadline", "attempts": attempt})
                    return
                continue
            finally:
                srv.release(backend)
            if status == 503 and unavailable_503 < len(srv.backends):
                # draining/overloaded replica: give the others a shot,
                # but relay the 503 once every backend said it
                unavailable_503 += 1
                tried_this_round.add(backend.addr)
                _M_PROXY_RETRIES.inc()
                if time.monotonic() <= deadline:
                    continue
            _M_PROXY_LATENCY.observe(time.perf_counter() - t0)
            self._reply(status, payload, headers=headers)
            return

    def _try_backend(self, srv: FleetProxy, backend: _Backend,
                     method: str, body: Optional[bytes]):
        conn = http.client.HTTPConnection(
            backend.host, backend.port, timeout=srv.backend_timeout_s)
        try:
            conn.request(method, self.path, body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            payload = resp.read()
            return resp.status, resp.getheaders(), payload
        finally:
            conn.close()


# ----------------------------------------------------------------------
# fleet launcher — N serve subprocesses + the proxy
# ----------------------------------------------------------------------
FLEET_DEFAULTS = {
    "replicas": 2,
    "port": 9095,
    "base_port": 0,
    "health_poll_ms": 500,
    "retry_deadline_ms": 10000,
    "ready_timeout_ms": 120000,
}


def _free_ports(n: int, host: str = "127.0.0.1") -> List[int]:
    import socket

    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind((host, 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


def _wait_ready(host: str, port: int, timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            conn = http.client.HTTPConnection(host, port, timeout=2.0)
            try:
                conn.request("GET", "/readyz")
                if conn.getresponse().status == 200:
                    return True
            finally:
                conn.close()
        except (OSError, http.client.HTTPException):
            pass
        time.sleep(0.1)
    return False


def spawn_replicas(n: int, serve_params: Dict[str, str],
                   ports: Optional[List[int]] = None,
                   host: str = "127.0.0.1") -> List[Tuple[subprocess.Popen, int]]:
    """Launch ``n`` ``python -m lightgbm_tpu serve`` subprocesses."""
    ports = ports or _free_ports(n, host)
    procs = []
    for port in ports[:n]:
        argv = [sys.executable, "-m", "lightgbm_tpu", "serve",
                f"host={host}", f"port={port}"]
        argv += [f"{k}={v}" for k, v in serve_params.items()]
        procs.append((subprocess.Popen(argv), port))
    return procs


def main(argv: List[str]) -> int:
    """``python -m lightgbm_tpu fleet model=...|registry=... replicas=N
    port=... [backends=h:p,h:p] [policy=least_loaded|rr] [serve knobs]``.

    With ``backends=`` the proxy fronts already-running replicas;
    otherwise it spawns ``replicas`` serve subprocesses (sharing
    ``registry=`` when given, so one publish hot-swaps the whole fleet)
    and supervises them.  SIGTERM drains: replicas get SIGTERM (their
    own graceful drain), then the proxy stops."""
    from ..cli import parse_argv

    tracer.refresh_from_env()
    params = parse_argv(argv)
    opts = dict(FLEET_DEFAULTS)
    for k in list(opts):
        if k in params:
            opts[k] = type(opts[k])(float(params[k]))
    host = str(params.get("host", "127.0.0.1"))
    policy = str(params.get("policy", "least_loaded"))

    procs: List[Tuple[subprocess.Popen, int]] = []
    if params.get("backends"):
        backends = [b.strip() for b in params["backends"].split(",")
                    if b.strip()]
    else:
        if not (params.get("model") or params.get("registry")):
            Log.warning("fleet: need model=..., registry=..., or "
                        "backends=host:port,...")
            return 1
        passthrough = {
            k: v for k, v in params.items()
            if k not in ("host", "port", "replicas", "base_port", "policy",
                         "backends", "health_poll_ms", "retry_deadline_ms",
                         "ready_timeout_ms")
        }
        n = int(opts["replicas"])
        ports = (list(range(int(opts["base_port"]),
                            int(opts["base_port"]) + n))
                 if int(opts["base_port"]) else None)
        procs = spawn_replicas(n, passthrough, ports=ports, host=host)
        backends = [f"{host}:{port}" for _, port in procs]
        for _, port in procs:
            if not _wait_ready(host, port,
                               float(opts["ready_timeout_ms"]) / 1e3):
                Log.warning("fleet: replica on port %d never became ready",
                            port)
                for p, _ in procs:
                    p.terminate()
                return 1
        Log.info("fleet: %d replica(s) ready on %s", n, backends)

    proxy = FleetProxy(
        (host, int(opts["port"])), backends, policy=policy,
        health_poll_s=float(opts["health_poll_ms"]) / 1e3,
        retry_deadline_s=float(opts["retry_deadline_ms"]) / 1e3,
    )
    bound = proxy.server_address[1]
    Log.info("fleet: proxy listening on http://%s:%d over %d backend(s)",
             host, bound, len(backends))

    def _on_sigterm(signum, frame):
        Log.warning("fleet: SIGTERM — draining replicas and stopping proxy")
        for p, _ in procs:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        threading.Thread(target=proxy.shutdown, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # pragma: no cover - embedded in a non-main thread
        pass

    try:
        proxy.serve_forever()
    except KeyboardInterrupt:
        _on_sigterm(signal.SIGINT, None)
        proxy.shutdown()
    finally:
        proxy.server_close()
        for p, _ in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
    Log.info("fleet: stopped")
    return 0
