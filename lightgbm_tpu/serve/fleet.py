"""Serving fleet: zero-downtime hot swap + replicated front end.

Two pieces, both riding on the existing serve/ layers:

``SwappablePredictor`` — the hot-swap slot.  One replica process holds
exactly one slot; the microbatchers' predict_fn samples the slot's
``(version, PackedPredictor)`` pointer ONCE per device batch, so every
batch — and therefore every request — is served by exactly one model
version even while a swap lands.  ``swap_to`` loads and ``warmup()``s
the incoming artifact in the calling (background) thread while traffic
keeps flowing on the old model, flips the pointer at a microbatch
boundary, then waits for the old version's in-flight batches to drain.
Because the compile cache is keyed on tree SHAPE, not model identity
(serve/compilecache.tree_shape_bucket), a retrain with the same
``num_trees/num_leaves`` inherits every warm XLA program: the swap
compiles NOTHING (pinned by tests/test_fleet.py).

``FleetProxy`` — a tiny stdlib-HTTP load-balancing front end over N
replica processes: round-robin or least-loaded backend choice,
per-replica health ejection (a dead or connection-refusing backend is
ejected and retried elsewhere within the same request — predict is
idempotent, so a SIGKILLed replica mid-request costs a retry, never a
dropped response), and a background ``/readyz`` prober that restores
recovered backends.  ``python -m lightgbm_tpu fleet`` spawns N
``serve`` subprocesses on a shared model registry plus the proxy.

Crash failures are the easy third of the story; the proxy also holds
the gray-failure line (docs/ROBUSTNESS.md):

- **deadline propagation** — a client ``X-Deadline-Ms`` budget bounds
  the whole relay; each backend attempt gets the shrunken remainder
  and a matching socket timeout, so a hung replica costs a bounded
  slice of the budget instead of the full 30 s socket timeout;
- **hedged requests** — an idempotent predict that outlives the hedge
  delay (fixed, or adaptive p95 of recent attempt latencies) fires one
  extra attempt at a different backend, first answer wins, volume
  capped by a budget counter;
- **latency-outlier circuit breakers** (serve/breaker.py) — per-backend
  latency/error EWMA vs the fleet median opens a breaker on a replica
  that is alive-but-wedged (``/readyz`` 200, ``/predict`` hangs — the
  mode the health prober can never see) and restores it through a
  single half-open probe;
- **overload control** — bounded proxy concurrency + bounded wait
  queue; excess load is shed with 503 + ``Retry-After`` instead of an
  unbounded thread pile.
"""

from __future__ import annotations

import http.client
import json
import os
import queue
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import compilewatch, tracer
from ..obs.metrics import (LATENCY_BUCKETS, RollingQuantile,
                           registry as metrics_registry)
from ..utils.log import Log
from . import breaker as breaker_mod
from .artifact import PackedPredictor, PredictorArtifact

_M_SWAPS = metrics_registry.counter(
    "lightgbm_tpu_serve_model_swaps_total",
    "completed hot swaps to a new model version")
_M_SWAP_SECONDS = metrics_registry.histogram(
    "lightgbm_tpu_serve_swap_seconds",
    "hot-swap latency: artifact load + warmup to traffic on the new model",
    buckets=LATENCY_BUCKETS)
_M_SWAP_COMPILES = metrics_registry.counter(
    "lightgbm_tpu_serve_swap_compiles_total",
    "XLA compiles attributable to hot swaps (0 for same-shape retrains)")
_M_PROXY_REQS = metrics_registry.counter(
    "lightgbm_tpu_proxy_requests_total", "requests handled by the proxy")
_M_PROXY_RETRIES = metrics_registry.counter(
    "lightgbm_tpu_proxy_retries_total",
    "request attempts re-routed to another backend")
_M_PROXY_EJECTIONS = metrics_registry.counter(
    "lightgbm_tpu_proxy_ejections_total",
    "backends ejected after a connection failure")
_M_PROXY_LATENCY = metrics_registry.histogram(
    "lightgbm_tpu_proxy_latency_seconds",
    "proxy request latency including retries", buckets=LATENCY_BUCKETS)
_M_PROXY_CANARY = metrics_registry.counter(
    "lightgbm_tpu_proxy_canary_requests_total",
    "predict requests answered by the canary backend")
_M_PROXY_HEDGES = metrics_registry.counter(
    "lightgbm_tpu_proxy_hedges_total",
    "hedge attempts launched for slow predicts")
_M_PROXY_HEDGE_WINS = metrics_registry.counter(
    "lightgbm_tpu_proxy_hedge_wins_total",
    "predicts where the hedge attempt answered first")
_M_PROXY_BREAKER_OPENS = metrics_registry.counter(
    "lightgbm_tpu_proxy_breaker_opens_total",
    "circuit-breaker CLOSED/HALF_OPEN -> OPEN transitions")
_M_PROXY_BREAKER_CLOSES = metrics_registry.counter(
    "lightgbm_tpu_proxy_breaker_closes_total",
    "circuit-breaker HALF_OPEN -> CLOSED restorations")
_M_PROXY_SHED = metrics_registry.counter(
    "lightgbm_tpu_proxy_shed_total",
    "requests shed by proxy overload control (503 + Retry-After)")
_M_PROXY_DEADLINE = metrics_registry.counter(
    "lightgbm_tpu_proxy_deadline_rejected_total",
    "requests 504ed at the proxy because the X-Deadline-Ms budget ran out")


# ----------------------------------------------------------------------
# hot-swap slot
# ----------------------------------------------------------------------
class SwappablePredictor:
    """Version-stamped predictor slot with zero-downtime swap.

    ``predict`` returns ``(outputs, version)``: the MicroBatcher calls
    it once per device batch, so the version is sampled exactly once per
    batch — the concurrent-swap attribution contract."""

    def __init__(self, predictor: PackedPredictor, version: int = 1):
        self._lock = threading.Lock()
        self._drain_cv = threading.Condition(self._lock)
        self._current: Tuple[int, PackedPredictor] = (int(version), predictor)
        self._inflight: Dict[int, int] = {}
        self._swaps = 0
        self.last_swap: Dict = {}
        metrics_registry.gauge(
            "lightgbm_tpu_serve_model_version",
            "model version currently receiving traffic",
            fn=lambda: float(self.version))
        metrics_registry.gauge(
            "lightgbm_tpu_serve_draining_model_versions",
            "old model versions still finishing in-flight batches",
            fn=lambda: float(self.draining_versions))

    # -- introspection -------------------------------------------------
    @property
    def version(self) -> int:
        return self._current[0]

    @property
    def predictor(self) -> PackedPredictor:
        return self._current[1]

    @property
    def artifact(self) -> PredictorArtifact:
        return self._current[1].artifact

    @property
    def num_features(self) -> int:
        return self._current[1].num_features

    @property
    def swaps(self) -> int:
        return self._swaps

    @property
    def draining_versions(self) -> int:
        with self._lock:
            cur = self._current[0]
            return sum(1 for v, n in self._inflight.items()
                       if v != cur and n > 0)

    # -- serving path --------------------------------------------------
    def predict(self, batch: np.ndarray, raw_score: bool = False):
        """(outputs, version) — the whole batch runs on ONE model."""
        with self._lock:
            ver, pred = self._current
            self._inflight[ver] = self._inflight.get(ver, 0) + 1
        try:
            out = pred.predict(batch, raw_score=raw_score)
        finally:
            with self._drain_cv:
                self._inflight[ver] -= 1
                if self._inflight[ver] <= 0:
                    self._inflight.pop(ver, None)
                    self._drain_cv.notify_all()
        return out, ver

    def warmup(self, max_rows: int) -> Dict:
        return self._current[1].warmup(max_rows)

    # -- swap ----------------------------------------------------------
    def swap_to(self, artifact: PredictorArtifact, version: int,
                warmup_max_rows: int = 4096, do_warmup: bool = True,
                drain_timeout_s: float = 30.0) -> Dict:
        """Zero-downtime swap: build + warm the new predictor while the
        old one keeps serving, flip the pointer (the next microbatch
        runs on the new model), then wait for the old version's
        in-flight batches to drain.  Returns swap stats including the
        compile count the swap cost (0 for a same-shape retrain)."""
        t0 = time.perf_counter()
        c0 = compilewatch.total_compiles()
        new_pred = PackedPredictor(artifact)
        if do_warmup:
            new_pred.warmup(warmup_max_rows)
        with self._lock:
            old_ver, _old_pred = self._current
            self._current = (int(version), new_pred)
            self._swaps += 1
        swap_s = time.perf_counter() - t0
        new_compiles = compilewatch.total_compiles() - c0
        drained = self._wait_version_drained(old_ver, drain_timeout_s)
        stats = {
            "from_version": int(old_ver),
            "to_version": int(version),
            "swap_ms": round(1e3 * swap_s, 3),
            "new_compiles": int(new_compiles),
            "old_drained": bool(drained),
        }
        self.last_swap = stats
        _M_SWAPS.inc()
        _M_SWAP_SECONDS.observe(swap_s)
        if new_compiles > 0:
            _M_SWAP_COMPILES.inc(new_compiles)
        tracer.event("serve.swap", **stats)
        Log.info("serve: hot-swapped model v%d -> v%d in %.1f ms "
                 "(%d new compiles, old %s)", old_ver, version,
                 stats["swap_ms"], new_compiles,
                 "drained" if drained else "DRAIN TIMED OUT")
        return stats

    def _wait_version_drained(self, version: int, timeout_s: float) -> bool:
        deadline = time.monotonic() + float(timeout_s)
        with self._drain_cv:
            while self._inflight.get(version, 0) > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._drain_cv.wait(min(remaining, 0.1))
        return True


# ----------------------------------------------------------------------
# load-balancing proxy
# ----------------------------------------------------------------------
class _Backend:
    __slots__ = ("host", "port", "healthy", "inflight", "requests",
                 "failures", "ejections")

    def __init__(self, addr: str):
        host, _, port = addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.healthy = True
        self.inflight = 0
        self.requests = 0
        self.failures = 0
        self.ejections = 0

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def as_dict(self) -> Dict:
        return {"addr": self.addr, "healthy": self.healthy,
                "inflight": self.inflight, "requests": self.requests,
                "failures": self.failures, "ejections": self.ejections}


class FleetProxy(ThreadingHTTPServer):
    """Round-robin / least-loaded HTTP proxy with health ejection.

    Local endpoints: ``/healthz`` (proxy liveness), ``/fleet/stats``
    (per-backend health + counters), ``/metrics`` (Prometheus).
    Everything else is forwarded to a healthy backend; connection
    failures eject the backend and the request retries elsewhere until
    ``retry_deadline_s`` — a response is dropped only when NO backend
    answers for that long."""

    daemon_threads = True

    def __init__(self, addr, backends: List[str], policy: str = "least_loaded",
                 backend_timeout_s: float = 30.0, health_poll_s: float = 0.5,
                 retry_deadline_s: float = 10.0,
                 hedge_delay_ms: float = 0.0, hedge_budget_pct: float = 10.0,
                 breaker_k: float = 3.0, breaker_m: int = 5,
                 breaker_open_ms: float = 2000.0,
                 max_concurrent: int = 128, max_queue: int = 256):
        if not backends:
            Log.fatal("fleet proxy needs at least one backend")
        if policy not in ("least_loaded", "rr"):
            Log.fatal("unknown proxy policy %r (least_loaded or rr)", policy)
        self.backends = [_Backend(b) for b in backends]
        self.policy = policy
        self.backend_timeout_s = float(backend_timeout_s)
        self.health_poll_s = float(health_poll_s)
        self.retry_deadline_s = float(retry_deadline_s)
        # gray-failure hardening (docs/ROBUSTNESS.md serving table):
        # hedge_delay_ms: fixed hedge trigger; 0 = adaptive (p95 of the
        # recent attempt-latency window); <0 disables hedging entirely
        self.hedge_delay_ms = float(hedge_delay_ms)
        self.hedge_budget_pct = float(hedge_budget_pct)
        self.breaker = breaker_mod.LatencyBreaker(
            k=float(breaker_k), m=int(breaker_m),
            open_s=float(breaker_open_ms) / 1e3)
        self.max_concurrent = int(max_concurrent)
        self.max_queue = int(max_queue)
        self._lat_window = RollingQuantile(window=512)
        self._fwd_requests = 0
        self._hedges = 0
        self._hedge_wins = 0
        self._shed = 0
        self._deadline_rejected = 0
        self._ocv = threading.Condition(threading.Lock())
        self._active = 0
        self._waiting = 0
        self._block = threading.Lock()
        self._rr = 0
        self._stop = threading.Event()
        self.t_start = time.time()
        # canary slice (docs/FACTORY.md): an out-of-rotation backend
        # pinned to the candidate version; a deterministic fraction of
        # /predict traffic is diverted to it, and a canary failure falls
        # back into the main pool so the client never pays for it
        self.canary: Optional[_Backend] = None
        self.canary_fraction = 0.0
        self._canary_tick = 0
        metrics_registry.gauge(
            "lightgbm_tpu_proxy_healthy_backends",
            "backends currently accepting traffic",
            fn=lambda: float(sum(1 for b in self.backends if b.healthy)))
        metrics_registry.gauge(
            "lightgbm_tpu_proxy_open_breakers",
            "backends whose circuit breaker is OPEN or HALF_OPEN",
            fn=lambda: float(self.breaker.open_count()))
        metrics_registry.gauge(
            "lightgbm_tpu_proxy_inflight_requests",
            "forwarded requests currently admitted by overload control",
            fn=lambda: float(self._active))
        self._health_thread = threading.Thread(
            target=self._health_loop, name="ltpu-fleet-health", daemon=True)
        super().__init__(addr, _ProxyHandler)
        self._health_thread.start()

    # -- backend choice ------------------------------------------------
    def pick(self, exclude: Optional[set] = None) -> Optional[_Backend]:
        exclude = exclude or set()
        with self._block:
            candidates = [b for b in self.backends
                          if b.healthy and b.addr not in exclude]
            if not candidates:
                # all excluded this attempt round: fall back to any
                # healthy backend (it may have recovered)
                candidates = [b for b in self.backends if b.healthy]
            if not candidates:
                return None
            # circuit breakers (serve/breaker.py): a due half-open probe
            # takes priority — that single request is what restores a
            # recovered backend; otherwise route among CLOSED backends,
            # and when every breaker is open fall back to all healthy
            # (breakers advise, they never zero out availability)
            trials = [b for b in candidates
                      if self.breaker.trial_eligible(b.addr)]
            if trials:
                candidates = trials
            else:
                closed = [b for b in candidates
                          if self.breaker.state(b.addr) == breaker_mod.CLOSED]
                if closed:
                    candidates = closed
            self._rr += 1
            if self.policy == "rr":
                chosen = candidates[self._rr % len(candidates)]
            else:
                # least-loaded, with a rotating tie-break so idle fleets
                # still spread sequential traffic instead of hammering
                # the first backend
                lo = min(b.inflight for b in candidates)
                tied = [b for b in candidates if b.inflight == lo]
                chosen = tied[self._rr % len(tied)]
            self.breaker.begin_attempt(chosen.addr)
            chosen.inflight += 1
            chosen.requests += 1
            return chosen

    def has_untried(self, tried: set) -> bool:
        """A healthy backend outside ``tried`` exists — the 503 re-route
        bound (counting against the live backend-list length shifts as
        backends eject/restore mid-request; the tried set does not)."""
        with self._block:
            return any(b.healthy and b.addr not in tried
                       for b in self.backends)

    def note_result(self, backend: _Backend, elapsed_s: float,
                    ok: bool) -> None:
        """Feed one attempt's outcome to the breaker + hedge-delay
        window and mirror breaker transitions to metrics/trace."""
        transition = self.breaker.observe(backend.addr, elapsed_s, ok)
        if ok:
            self._lat_window.observe(elapsed_s)
        if transition in ("open", "reopen"):
            _M_PROXY_BREAKER_OPENS.inc()
            Log.warning("fleet: breaker OPEN on %s (%s)", backend.addr,
                        "probe failed" if transition == "reopen"
                        else "latency/error outlier")
        elif transition == "close":
            _M_PROXY_BREAKER_CLOSES.inc()
            Log.info("fleet: breaker CLOSED on %s (probe succeeded)",
                     backend.addr)
        if transition:
            tracer.event("fleet.breaker", addr=backend.addr,
                         transition=transition)

    # -- hedging -------------------------------------------------------
    def hedge_delay_s(self) -> Optional[float]:
        """Current hedge trigger in seconds, or None when hedging is
        off (negative knob or a single-backend fleet)."""
        if self.hedge_delay_ms < 0 or len(self.backends) < 2:
            return None
        if self.hedge_delay_ms > 0:
            return self.hedge_delay_ms / 1e3
        # adaptive: p95 of the recent attempt-latency window, floored so
        # a microsecond-fast fleet does not hedge-storm, with a cold
        # fallback until the window has signal
        if self._lat_window.count() < 20:
            return 0.05
        return max(0.025, self._lat_window.quantile(0.95))

    def take_hedge_token(self) -> bool:
        """Hedge budget: hedges may not exceed ``hedge_budget_pct`` % of
        forwarded requests (with a small floor so early traffic can
        still hedge before the denominator grows)."""
        if self.hedge_budget_pct <= 0:
            return False
        with self._block:
            allowed = max(5.0,
                          self.hedge_budget_pct / 100.0 * self._fwd_requests)
            if self._hedges + 1 > allowed:
                return False
            self._hedges += 1
            return True

    # -- overload control ----------------------------------------------
    def admit(self, deadline: float) -> bool:
        """Bounded concurrency + bounded wait queue: a forwarded request
        either gets a concurrency slot (possibly after queueing until
        ``deadline``) or is shed — the proxy never grows an unbounded
        thread pile behind a slow fleet."""
        if self.max_concurrent <= 0:
            return True
        with self._ocv:
            if self._active < self.max_concurrent:
                self._active += 1
                return True
            if self._waiting >= self.max_queue:
                return False
            self._waiting += 1
            try:
                while self._active >= self.max_concurrent:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    self._ocv.wait(min(remaining, 0.05))
                self._active += 1
                return True
            finally:
                self._waiting -= 1

    def unadmit(self) -> None:
        if self.max_concurrent <= 0:
            return
        with self._ocv:
            self._active = max(0, self._active - 1)
            self._ocv.notify()

    # -- canary slice --------------------------------------------------
    def set_canary(self, addr: Optional[str],
                   fraction: float = 0.0) -> None:
        """Install (or clear with ``addr=None``/``fraction<=0``) the
        canary backend receiving ``fraction`` of /predict traffic."""
        with self._block:
            if addr and fraction > 0:
                self.canary = _Backend(addr)
                self.canary_fraction = min(1.0, float(fraction))
                self._canary_tick = 0
            else:
                self.canary = None
                self.canary_fraction = 0.0
        tracer.event("fleet.canary",
                     addr=str(addr) if addr and fraction > 0 else None,
                     fraction=float(self.canary_fraction))

    def pick_canary(self) -> Optional[_Backend]:
        """Deterministic fraction routing: predict request t diverts to
        the canary exactly when ``floor(t*f)`` advances — fraction f of
        traffic with no RNG and no burst (every 1/f-th request)."""
        with self._block:
            c = self.canary
            if c is None or not c.healthy:
                return None
            self._canary_tick += 1
            t, f = self._canary_tick, self.canary_fraction
            if int(t * f) <= int((t - 1) * f):
                return None
            c.inflight += 1
            c.requests += 1
            return c

    def release(self, backend: _Backend) -> None:
        with self._block:
            backend.inflight = max(0, backend.inflight - 1)

    def eject(self, backend: _Backend) -> None:
        with self._block:
            backend.failures += 1
            if backend.healthy:
                backend.healthy = False
                backend.ejections += 1
                _M_PROXY_EJECTIONS.inc()
                Log.warning("fleet: ejected backend %s after a "
                            "connection failure", backend.addr)

    # -- health probing ------------------------------------------------
    def _probe(self, backend: _Backend) -> bool:
        try:
            conn = http.client.HTTPConnection(backend.host, backend.port,
                                              timeout=2.0)
            try:
                conn.request("GET", "/readyz")
                return conn.getresponse().status == 200
            finally:
                conn.close()
        except OSError:
            return False
        except http.client.HTTPException:
            return False

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_poll_s):
            with self._block:
                c = self.canary
            probed = list(self.backends) + ([c] if c is not None else [])
            for b in probed:
                ok = self._probe(b)
                with self._block:
                    if ok and not b.healthy:
                        Log.info("fleet: backend %s recovered", b.addr)
                    b.healthy = ok

    # -- ops surface ---------------------------------------------------
    def stats(self) -> Dict:
        breakers = self.breaker.snapshot()
        with self._block:
            backends = [dict(b.as_dict(), breaker=breakers.get(b.addr))
                        for b in self.backends]
            canary = (dict(self.canary.as_dict(),
                           fraction=self.canary_fraction)
                      if self.canary is not None else None)
            hedges = {"launched": self._hedges, "wins": self._hedge_wins,
                      "budget_pct": self.hedge_budget_pct,
                      "delay_ms": self.hedge_delay_ms}
            deadline_rejected = self._deadline_rejected
            shed = self._shed
        with self._ocv:
            overload = {"active": self._active, "waiting": self._waiting,
                        "shed": shed,
                        "max_concurrent": self.max_concurrent,
                        "max_queue": self.max_queue}
        return {
            "uptime_s": round(time.time() - self.t_start, 1),
            "policy": self.policy,
            "healthy": sum(1 for b in backends if b["healthy"]),
            "backends": backends,
            "canary": canary,
            "hedges": hedges,
            "overload": overload,
            "open_breakers": sum(1 for s in breakers.values()
                                 if s["state"] != breaker_mod.CLOSED),
            "deadline_rejected": deadline_rejected,
        }

    def shutdown(self):
        self._stop.set()
        super().shutdown()


class _ProxyHandler(BaseHTTPRequestHandler):
    server_version = "lightgbm-tpu-fleet/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        Log.debug("fleet: " + fmt, *args)

    def _reply(self, code: int, payload: bytes,
               headers: Optional[List[Tuple[str, str]]] = None) -> None:
        self.send_response(code)
        sent = set()
        for k, v in headers or []:
            if k.lower() in ("content-type", "x-model-version",
                             "x-model-route", "retry-after"):
                self.send_header(k, v)
                sent.add(k.lower())
        if "content-type" not in sent:
            self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _reply_json(self, code: int, obj) -> None:
        self._reply(code, (json.dumps(obj) + "\n").encode())

    def do_GET(self):
        if self.path == "/healthz":
            self._reply_json(200, {"status": "ok", "role": "proxy"})
        elif self.path == "/fleet/stats":
            self._reply_json(200, self.server.stats())
        elif self.path == "/metrics":
            self._reply(200, metrics_registry.render().encode(),
                        headers=[("Content-Type",
                                  "text/plain; version=0.0.4; charset=utf-8")])
        else:
            self._forward("GET", body=None)

    def do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        if self.path == "/fleet/canary":
            self._do_canary(body)
            return
        self._forward("POST", body=body)

    def _do_canary(self, body: bytes) -> None:
        """POST /fleet/canary {"addr": "host:port", "fraction": 0.2} —
        install a canary slice; null addr or fraction<=0 clears it."""
        try:
            req = json.loads(body.decode("utf-8") or "{}")
            addr = req.get("addr")
            fraction = float(req.get("fraction") or 0.0)
        except (ValueError, AttributeError) as e:
            self._reply_json(400, {"error": f"bad canary request: {e}"})
            return
        self.server.set_canary(addr, fraction)
        with self.server._block:
            c = self.server.canary
            self._reply_json(200, {
                "canary": c.addr if c is not None else None,
                "fraction": self.server.canary_fraction,
            })

    def _deadline_budget_ms(self) -> Optional[float]:
        """Client ``X-Deadline-Ms`` budget, or None (absent/bad)."""
        raw = self.headers.get("X-Deadline-Ms")
        if not raw:
            return None
        try:
            v = float(raw)
        except ValueError:
            return None
        return v if v > 0 else 0.0

    def _forward(self, method: str, body: Optional[bytes]) -> None:
        """Relay to a healthy backend under the gray-failure contract:

        - ``X-Deadline-Ms`` budget bounds the WHOLE relay (attempts,
          queueing, retries); each backend attempt gets the shrunken
          remainder forwarded and a socket timeout no larger than it,
          so a hung replica costs a bounded timeout, never 30 s.
        - Connection failures eject-and-retry; 503s re-route until the
          set of backends *tried this round* is exhausted.
        - Idempotent predicts that outlive the hedge delay fire ONE
          hedge at a different backend; first response wins.
        - Admission control sheds with 503 + ``Retry-After`` instead of
          queueing unboundedly."""
        srv: FleetProxy = self.server
        t0 = time.perf_counter()
        tm0 = time.monotonic()
        _M_PROXY_REQS.inc()
        with srv._block:
            srv._fwd_requests += 1
        budget_ms = self._deadline_budget_ms()
        budget_deadline = (tm0 + budget_ms / 1e3
                           if budget_ms is not None else None)
        deadline = tm0 + srv.retry_deadline_s
        if budget_deadline is not None:
            deadline = min(deadline, budget_deadline)
        is_predict = (method == "POST"
                      and self.path.partition("?")[0].startswith("/predict"))
        if not srv.admit(deadline):
            with srv._block:
                srv._shed += 1
            _M_PROXY_SHED.inc()
            self._reply(503, (json.dumps(
                {"error": "proxy overloaded, retry later"}) + "\n").encode(),
                headers=[("Retry-After", "1")])
            return
        try:
            if budget_deadline is not None \
                    and time.monotonic() >= budget_deadline:
                self._reply_deadline_exceeded(srv, 0)
                return
            if is_predict and self.path.partition("?")[0] == "/predict":
                canary = srv.pick_canary()
                if canary is not None:
                    status = None
                    try:
                        status, headers, payload = self._try_backend(
                            srv, canary, method, body,
                            timeout_s=self._attempt_timeout(srv, deadline),
                            deadline_ms=self._remaining_ms(budget_deadline))
                    except (OSError, http.client.HTTPException):
                        # a canary that stops answering must not be
                        # re-picked and re-timed-out on every request
                        # until the prober notices: eject it like a
                        # main-pool backend
                        srv.eject(canary)
                    finally:
                        srv.release(canary)
                    if status is not None and status < 500 and status != 503:
                        _M_PROXY_CANARY.inc()
                        _M_PROXY_LATENCY.observe(time.perf_counter() - t0)
                        self._reply(status, payload, headers=headers)
                        return
                    # a failing canary never costs the client a
                    # response: fall back into the main pool.  The
                    # canary replica's own per-version error metrics
                    # carry the verdict evidence — the proxy only
                    # limits the blast radius.
                    _M_PROXY_RETRIES.inc()
            self._forward_pool(srv, method, body, t0, deadline,
                               budget_deadline, hedge_ok=is_predict)
        finally:
            srv.unadmit()

    @staticmethod
    def _attempt_timeout(srv: FleetProxy, deadline: float) -> float:
        return min(srv.backend_timeout_s,
                   max(deadline - time.monotonic(), 0.05))

    @staticmethod
    def _remaining_ms(budget_deadline: Optional[float]) -> Optional[float]:
        if budget_deadline is None:
            return None
        return max(0.0, (budget_deadline - time.monotonic()) * 1e3)

    def _reply_deadline_exceeded(self, srv: FleetProxy,
                                 attempts: int) -> None:
        with srv._block:
            srv._deadline_rejected += 1
        _M_PROXY_DEADLINE.inc()
        self._reply_json(504, {"error": "deadline exhausted",
                               "attempts": attempts})

    def _forward_pool(self, srv: FleetProxy, method: str,
                      body: Optional[bytes], t0: float, deadline: float,
                      budget_deadline: Optional[float],
                      hedge_ok: bool) -> None:
        """The attempt loop: worker threads race into a result queue so
        the handler can arm a hedge while the first attempt is still in
        flight.  At most one hedge per request; every launched attempt
        feeds the breaker when it eventually resolves."""
        resultq: "queue.Queue" = queue.Queue()
        tried: set = set()
        busy: set = set()  # addrs with an attempt currently in flight
        inflight = 0
        attempt = 0
        hedge_used = False
        last_503 = None

        def launch(backend: _Backend, is_hedge: bool) -> None:
            nonlocal inflight, attempt
            attempt += 1
            inflight += 1
            busy.add(backend.addr)
            timeout_s = self._attempt_timeout(srv, deadline)
            deadline_ms = self._remaining_ms(budget_deadline)
            t_launch = time.monotonic()

            def run():
                # breaker feeding + ejection live HERE, in the attempt
                # thread: a hung attempt whose handler already answered
                # via hedge still lands its timeout on the breaker —
                # that orphaned observation is exactly the gray-failure
                # evidence the breaker exists to accumulate
                try:
                    out = self._try_backend(srv, backend, method, body,
                                            timeout_s=timeout_s,
                                            deadline_ms=deadline_ms)
                    srv.note_result(backend,
                                    time.monotonic() - t_launch,
                                    ok=out[0] < 500)
                    resultq.put((backend, is_hedge, t_launch, None, out))
                except (OSError, http.client.HTTPException) as e:
                    srv.note_result(backend,
                                    time.monotonic() - t_launch, ok=False)
                    srv.eject(backend)
                    resultq.put((backend, is_hedge, t_launch, e, None))
                finally:
                    srv.release(backend)

            threading.Thread(target=run, daemon=True,
                             name="ltpu-fleet-attempt").start()

        def give_up(now: float) -> None:
            # the client's budget is spent (attempts may still be in
            # flight) — answer now, bounded: the best 503 we saw, a 504
            # for an exhausted client deadline, a 502 otherwise
            if last_503 is not None:
                status, headers, payload = last_503
                self._reply(status, payload, headers=headers)
            elif budget_deadline is not None and now >= budget_deadline:
                self._reply_deadline_exceeded(srv, attempt)
            else:
                self._reply_json(502, {
                    "error": "no backend answered before the retry "
                             "deadline", "attempts": attempt})

        while True:
            if inflight == 0:
                if time.monotonic() > deadline:
                    give_up(time.monotonic())
                    return
                backend = srv.pick(exclude=tried)
                if backend is None:
                    time.sleep(0.05)
                    tried.clear()  # health loop may restore one
                    continue
                launch(backend, is_hedge=False)
            # wait for a result; while the FIRST attempt is alone in
            # flight an un-hedged predict wakes early at the hedge delay
            wait_s = max(deadline - time.monotonic(), 0.001)
            hd = srv.hedge_delay_s() if (hedge_ok and not hedge_used
                                         and inflight == 1) else None
            if hd is not None:
                wait_s = min(wait_s, hd)
            try:
                backend, is_hedge, t_launch, err, out = resultq.get(
                    timeout=wait_s)
            except queue.Empty:
                now = time.monotonic()
                if now > deadline:
                    give_up(now)
                    return
                if hd is not None and not hedge_used:
                    hedge_used = True  # one hedge per request, ever
                    if srv.take_hedge_token():
                        # a hedge at the backend the stuck attempt is
                        # already on is no hedge at all: exclude busy
                        # addrs, and skip entirely if pick's all-healthy
                        # fallback re-includes one (hung single-survivor
                        # fleets just wait out the first attempt)
                        hb = srv.pick(exclude=tried | busy)
                        if hb is not None and hb.addr in busy:
                            srv.release(hb)
                        elif hb is not None:
                            _M_PROXY_HEDGES.inc()
                            launch(hb, is_hedge=True)
                continue
            inflight -= 1
            busy.discard(backend.addr)
            if err is not None:
                tried.add(backend.addr)
                _M_PROXY_RETRIES.inc()
                continue
            status, headers, payload = out
            if status == 503:
                tried.add(backend.addr)
                last_503 = (status, headers, payload)
                if srv.has_untried(tried) and time.monotonic() <= deadline:
                    # draining/overloaded replica: give the others a
                    # shot, but relay the 503 once every backend
                    # actually tried this round said it
                    _M_PROXY_RETRIES.inc()
                    continue
                if inflight > 0:
                    continue  # a raced attempt may still answer
            elif is_hedge:
                with srv._block:
                    srv._hedge_wins += 1
                _M_PROXY_HEDGE_WINS.inc()
            _M_PROXY_LATENCY.observe(time.perf_counter() - t0)
            self._reply(status, payload, headers=headers)
            return

    def _try_backend(self, srv: FleetProxy, backend: _Backend,
                     method: str, body: Optional[bytes],
                     timeout_s: Optional[float] = None,
                     deadline_ms: Optional[float] = None):
        conn = http.client.HTTPConnection(
            backend.host, backend.port,
            timeout=timeout_s if timeout_s else srv.backend_timeout_s)
        headers = {"Content-Type": "application/json"}
        if deadline_ms is not None:
            # each hop forwards the SHRUNKEN remainder: the replica sees
            # how much of the client's budget is actually left
            headers["X-Deadline-Ms"] = str(int(deadline_ms))
        try:
            conn.request(method, self.path, body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            return resp.status, resp.getheaders(), payload
        finally:
            conn.close()


# ----------------------------------------------------------------------
# fleet launcher — N serve subprocesses + the proxy
# ----------------------------------------------------------------------
FLEET_DEFAULTS = {
    "replicas": 2,
    "port": 9095,
    "base_port": 0,
    "health_poll_ms": 500,
    "retry_deadline_ms": 10000,
    "ready_timeout_ms": 120000,
    "backend_timeout_ms": 30000,
    "hedge_delay_ms": 0.0,       # 0 = adaptive p95; <0 disables hedging
    "hedge_budget_pct": 10.0,
    "breaker_k": 3.0,
    "breaker_m": 5,
    "breaker_open_ms": 2000,
    "max_concurrent": 128,
    "max_queue": 256,
}


def _free_ports(n: int, host: str = "127.0.0.1") -> List[int]:
    import socket

    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind((host, 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


def _wait_ready(host: str, port: int, timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            conn = http.client.HTTPConnection(host, port, timeout=2.0)
            try:
                conn.request("GET", "/readyz")
                if conn.getresponse().status == 200:
                    return True
            finally:
                conn.close()
        except (OSError, http.client.HTTPException):
            pass
        time.sleep(0.1)
    return False


def spawn_replicas(n: int, serve_params: Dict[str, str],
                   ports: Optional[List[int]] = None,
                   host: str = "127.0.0.1",
                   envs: Optional[List[Optional[Dict[str, str]]]] = None,
                   ) -> List[Tuple[subprocess.Popen, int]]:
    """Launch ``n`` ``python -m lightgbm_tpu serve`` subprocesses.

    ``envs[i]`` overlays extra environment onto replica ``i`` — how the
    chaos harness and bench arm per-replica fault injection
    (``LIGHTGBM_TPU_SERVE_FAULT``) without touching the shared argv."""
    ports = ports or _free_ports(n, host)
    procs = []
    for i, port in enumerate(ports[:n]):
        argv = [sys.executable, "-m", "lightgbm_tpu", "serve",
                f"host={host}", f"port={port}"]
        argv += [f"{k}={v}" for k, v in serve_params.items()]
        env = None
        if envs and i < len(envs) and envs[i]:
            env = dict(os.environ)
            env.update(envs[i])
        procs.append((subprocess.Popen(argv, env=env), port))
    return procs


def main(argv: List[str]) -> int:
    """``python -m lightgbm_tpu fleet model=...|registry=... replicas=N
    port=... [backends=h:p,h:p] [policy=least_loaded|rr] [serve knobs]``.

    With ``backends=`` the proxy fronts already-running replicas;
    otherwise it spawns ``replicas`` serve subprocesses (sharing
    ``registry=`` when given, so one publish hot-swaps the whole fleet)
    and supervises them.  SIGTERM drains: replicas get SIGTERM (their
    own graceful drain), then the proxy stops."""
    from ..cli import parse_argv

    tracer.refresh_from_env()
    params = parse_argv(argv)
    opts = dict(FLEET_DEFAULTS)
    for k in list(opts):
        if k in params:
            opts[k] = type(opts[k])(float(params[k]))
    host = str(params.get("host", "127.0.0.1"))
    policy = str(params.get("policy", "least_loaded"))

    procs: List[Tuple[subprocess.Popen, int]] = []
    if params.get("backends"):
        backends = [b.strip() for b in params["backends"].split(",")
                    if b.strip()]
    else:
        if not (params.get("model") or params.get("registry")):
            Log.warning("fleet: need model=..., registry=..., or "
                        "backends=host:port,...")
            return 1
        passthrough = {
            k: v for k, v in params.items()
            if k not in ("host", "port", "replicas", "base_port", "policy",
                         "backends", "health_poll_ms", "retry_deadline_ms",
                         "ready_timeout_ms", "backend_timeout_ms",
                         "hedge_delay_ms", "hedge_budget_pct", "breaker_k",
                         "breaker_m", "breaker_open_ms", "max_concurrent",
                         "max_queue")
        }
        n = int(opts["replicas"])
        ports = (list(range(int(opts["base_port"]),
                            int(opts["base_port"]) + n))
                 if int(opts["base_port"]) else None)
        procs = spawn_replicas(n, passthrough, ports=ports, host=host)
        backends = [f"{host}:{port}" for _, port in procs]
        for _, port in procs:
            if not _wait_ready(host, port,
                               float(opts["ready_timeout_ms"]) / 1e3):
                Log.warning("fleet: replica on port %d never became ready",
                            port)
                for p, _ in procs:
                    p.terminate()
                return 1
        Log.info("fleet: %d replica(s) ready on %s", n, backends)

    proxy = FleetProxy(
        (host, int(opts["port"])), backends, policy=policy,
        backend_timeout_s=float(opts["backend_timeout_ms"]) / 1e3,
        health_poll_s=float(opts["health_poll_ms"]) / 1e3,
        retry_deadline_s=float(opts["retry_deadline_ms"]) / 1e3,
        hedge_delay_ms=float(opts["hedge_delay_ms"]),
        hedge_budget_pct=float(opts["hedge_budget_pct"]),
        breaker_k=float(opts["breaker_k"]),
        breaker_m=int(opts["breaker_m"]),
        breaker_open_ms=float(opts["breaker_open_ms"]),
        max_concurrent=int(opts["max_concurrent"]),
        max_queue=int(opts["max_queue"]),
    )
    bound = proxy.server_address[1]
    Log.info("fleet: proxy listening on http://%s:%d over %d backend(s)",
             host, bound, len(backends))

    def _on_sigterm(signum, frame):
        Log.warning("fleet: SIGTERM — draining replicas and stopping proxy")
        for p, _ in procs:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        threading.Thread(target=proxy.shutdown, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # pragma: no cover - embedded in a non-main thread
        pass

    try:
        proxy.serve_forever()
    except KeyboardInterrupt:
        _on_sigterm(signal.SIGINT, None)
        proxy.shutdown()
    finally:
        proxy.server_close()
        for p, _ in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
    Log.info("fleet: stopped")
    return 0
