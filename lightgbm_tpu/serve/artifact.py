"""Packed predictor artifacts — the serving-side model format.

A trained model's inference state is exactly the stacked SoA node arrays
that ``ops/predict.predict_raw`` traverses (``TreeArrays``) plus a small
metadata record (objective string, class count, feature names).  The
training-side model text format (``GBDT::SaveModelToString``) keeps
reference compatibility but pays a full host-side reparse through
``Tree.from_string`` + ``stack_trees`` on every cold start; a packed
artifact freezes the post-``stack_trees`` arrays into one versioned
``.npz`` so a server loads with ``np.load`` and starts answering after
``warmup()``.

Format (``.npz``, version 1):
  ``__meta__``           0-d array holding one JSON string (see META_KEYS)
  ``<TreeArrays field>`` one entry per ``TreeArrays.FIELDS`` name, with
                         the (T, M)/(T, L) shapes ``TreeArrays.validate``
                         checks.  Tree order is model order (class of
                         tree ``i`` is ``i % num_tree_per_iteration``).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from ..ops.predict import LinearTreeArrays, TreeArrays
from ..utils.log import Log

FORMAT_VERSION = 1  # exact flavor — byte-stable since PR 9
QUANT_FORMAT_VERSION = 2  # quantized flavor (meta carries "flavor")
LINEAR_FORMAT_VERSION = 3  # linear-leaf flavor (tree/linear.py plug-in)
SUPPORTED_VERSIONS = (FORMAT_VERSION, QUANT_FORMAT_VERSION,
                      LINEAR_FORMAT_VERSION)
META_KEYS = (
    "format_version",
    "num_class",
    "num_tree_per_iteration",
    "num_trees",
    "num_features",
    "objective",
    "boost_from_average",
    "feature_names",
    "pandas_categorical",
)
# quantized (format_version 2) artifacts additionally require these
QUANT_META_KEYS = ("flavor", "levels", "leaf_dtype")
# linear (format_version 3) artifacts additionally require these
LINEAR_META_KEYS = ("flavor",)

# stack_trees() dict key -> TreeArrays field name (the stacker predates
# TreeArrays and names the real-feature plane "split_feature")
_STACK_TO_FIELD = {
    "split_feature_inner": "split_feature",
    "split_feature": "split_feature_real",
    "threshold_bin": "threshold_bin",
    "threshold_real": "threshold_real",
    "threshold_real_lo": "threshold_real_lo",
    "threshold_real_lo2": "threshold_real_lo2",
    "zero_bin": "zero_bin",
    "default_bin_for_zero": "default_bin_for_zero",
    "default_value": "default_value_real",
    "default_value_lo": "default_value_real_lo",
    "default_value_lo2": "default_value_real_lo2",
    "is_categorical": "is_categorical",
    "left_child": "left_child",
    "right_child": "right_child",
    "leaf_value": "leaf_value",
    # linear-leaf coefficient planes (v3; leaf_feat_inner is a
    # training-side plane the raw-serving artifact does not carry)
    "leaf_feat_real": "leaf_feat_real",
    "leaf_feat_valid": "leaf_feat_valid",
    "leaf_coeff": "leaf_coeff",
    "leaf_const": "leaf_const",
    "leaf_is_linear": "leaf_is_linear",
}


def stacked_tree_arrays(models: List) -> TreeArrays:
    """Stack host Trees into a host-side (numpy) ``TreeArrays`` —
    ``LinearTreeArrays`` when any tree carries linear leaf models."""
    from ..model.ensemble import stack_trees

    stacked = stack_trees(models)
    fields = {
        _STACK_TO_FIELD[k]: np.asarray(v)
        for k, v in stacked.items()
        if k in _STACK_TO_FIELD
    }
    if "leaf_coeff" in fields:
        return LinearTreeArrays(**fields).validate()
    return TreeArrays(**fields).validate()


class PredictorArtifact:
    """Host-side packed model: a ``TreeArrays`` (exact flavor) or a
    ``QTreeArrays`` (quantized flavor) + metadata dict."""

    def __init__(self, arrays, meta: Dict):
        self.arrays = arrays
        self.meta = dict(meta)
        self.validate()

    # -- construction --------------------------------------------------
    @classmethod
    def from_booster(cls, booster, num_iteration: int = -1,
                     quantized: bool = False,
                     leaf_dtype: str = "float16") -> "PredictorArtifact":
        """Freeze a trained/loaded ``Booster``'s inference state.

        ``quantized=True`` packs the int16 rank-quantized flavor
        (format_version 2, see ops/qpredict.py) instead of the exact
        triple-float arrays; the exact flavor stays the default and the
        bit-exact reference."""
        b = booster.boosting
        models = b._used_models(num_iteration)
        if not models:
            Log.fatal("Cannot pack an artifact from a model with no trees")
        if b.objective is not None:
            objective = b.objective.to_string()
        else:
            objective = getattr(b, "objective_name_loaded", "") or ""
        meta = {
            "format_version": FORMAT_VERSION,
            "num_class": int(b.num_class),
            "num_tree_per_iteration": int(b.num_tree_per_iteration),
            "num_trees": len(models),
            "num_features": int(b.max_feature_idx) + 1,
            "objective": objective,
            "boost_from_average": bool(b.boost_from_average_),
            "feature_names": list(b.feature_names or []),
            "pandas_categorical": getattr(booster, "pandas_categorical", []) or [],
        }
        arrays = stacked_tree_arrays(models)
        if isinstance(arrays, LinearTreeArrays):
            meta["format_version"] = LINEAR_FORMAT_VERSION
            meta["flavor"] = "linear"
        art = cls(arrays, meta)
        return art.quantize(leaf_dtype) if quantized else art

    @property
    def flavor(self) -> str:
        return str(self.meta.get("flavor", "exact"))

    def quantize(self, leaf_dtype: str = "float16") -> "PredictorArtifact":
        """The quantized flavor of this artifact (exact route parity;
        see ops/qpredict.py).  Quantizing a quantized artifact returns
        it unchanged."""
        if self.flavor == "quantized":
            return self
        if self.flavor == "linear":
            Log.fatal(
                "Quantized serving does not support linear-leaf (v3) "
                "artifacts — the int16 rank-quantized traversal has no "
                "coefficient planes; serve the exact linear path, or "
                "retrain with linear_tree=false to quantize")
        from ..ops.qpredict import quantize_tree_arrays

        q = quantize_tree_arrays(self.arrays, leaf_dtype=leaf_dtype,
                                 num_features=self.num_features)
        meta = dict(self.meta)
        meta["format_version"] = QUANT_FORMAT_VERSION
        meta["flavor"] = "quantized"
        meta["levels"] = int(q.levels)
        meta["leaf_dtype"] = q.leaf_dtype
        return PredictorArtifact(q, meta)

    # -- persistence ---------------------------------------------------
    def _payload(self) -> Dict[str, np.ndarray]:
        if self.flavor == "quantized":
            from ..ops.qpredict import QTreeArrays

            payload = {f: np.asarray(getattr(self.arrays, f))
                       for f in QTreeArrays.FIELDS}
            # bfloat16 is not a native numpy dtype — persist raw bits;
            # meta["leaf_dtype"] tells the loader how to view them back
            if self.meta.get("leaf_dtype") == "bfloat16":
                payload["leaf_value"] = payload["leaf_value"].view(np.uint16)
        elif self.flavor == "linear":
            payload = {f: np.asarray(getattr(self.arrays, f))
                       for f in LinearTreeArrays.FIELDS}
        else:
            payload = {f: getattr(self.arrays, f) for f in TreeArrays.FIELDS}
        payload["__meta__"] = np.asarray(json.dumps(self.meta))
        return payload

    def save(self, path: str) -> str:
        np.savez_compressed(path, **self._payload())
        # np.savez appends .npz when missing — report the real path
        return path if path.endswith(".npz") else path + ".npz"

    def save_to_bytes(self, buf) -> None:
        """Serialize into a writable binary file-like (the registry
        publishes artifacts as bytes, never touching a temp path)."""
        np.savez_compressed(buf, **self._payload())

    @classmethod
    def load(cls, path: str) -> "PredictorArtifact":
        """Load a packed artifact, refusing anything that is not a
        trustworthy current-format file with an actionable message
        (mirrors the data/cache.py v2 refusal semantics): a corrupt or
        truncated file, a future format version, and a missing field
        set each name the remedy instead of surfacing a raw numpy
        error."""
        try:
            z = np.load(path, allow_pickle=False)
        except Exception as e:
            # numpy raises OSError/ValueError/zipfile.BadZipFile
            # depending on where the file is broken — fold them all into
            # one actionable refusal, but never mask our own fatals
            from ..utils.log import LightGBMError

            if isinstance(e, LightGBMError):
                raise
            Log.fatal(
                "%s is not a readable packed predictor artifact (%s: %s) "
                "— the file is corrupt, truncated, or not an artifact; "
                "re-pack it with PredictorArtifact.save / POST /models",
                path, type(e).__name__, e)
        with z:
            return cls._from_npz(z, path)

    @classmethod
    def load_bytes(cls, blob: bytes) -> "PredictorArtifact":
        """Load from in-memory ``.npz`` bytes (registry blobs, POST
        /models upload bodies) with the same refusal semantics as
        ``load``."""
        import io

        from ..utils.log import LightGBMError

        try:
            z = np.load(io.BytesIO(blob), allow_pickle=False)
        except Exception as e:
            if isinstance(e, LightGBMError):
                raise
            Log.fatal(
                "artifact bytes are not a readable packed predictor "
                "artifact (%s: %s) — corrupt or truncated upload",
                type(e).__name__, e)
        with z:
            return cls._from_npz(z, "<bytes>")

    @classmethod
    def _from_npz(cls, z, origin: str) -> "PredictorArtifact":
        if "__meta__" not in z:
            Log.fatal(
                "%s is not a packed predictor artifact (no __meta__ "
                "entry); pack the model with PredictorArtifact.save",
                origin)
        try:
            meta = json.loads(str(z["__meta__"]))
        except ValueError:
            Log.fatal("%s carries an unparseable __meta__ header — the "
                      "artifact is corrupt; re-pack it", origin)
        version = int(meta.get("format_version", -1))
        if version > max(SUPPORTED_VERSIONS):
            Log.fatal(
                "%s was written by a NEWER lightgbm_tpu (artifact "
                "format_version %d, this build supports <= %d) — upgrade "
                "this serving process, or re-pack the model with this "
                "build", origin, version, max(SUPPORTED_VERSIONS))
        if version not in SUPPORTED_VERSIONS:
            Log.fatal(
                "%s uses unsupported artifact format_version %s "
                "(supported: %s) — re-pack the model with "
                "PredictorArtifact.save", origin, version,
                "/".join(str(v) for v in SUPPORTED_VERSIONS))
        if version == QUANT_FORMAT_VERSION:
            if meta.get("flavor") != "quantized":
                Log.fatal(
                    "%s claims artifact format_version %d but flavor %r "
                    "(expected 'quantized') — the header is inconsistent; "
                    "re-pack it", origin, version, meta.get("flavor"))
            from ..ops.qpredict import QTreeArrays, _leaf_np_dtype

            field_set = QTreeArrays.FIELDS
        elif version == LINEAR_FORMAT_VERSION:
            if meta.get("flavor") != "linear":
                Log.fatal(
                    "%s claims artifact format_version %d but flavor %r "
                    "(expected 'linear') — the header is inconsistent; "
                    "re-pack it", origin, version, meta.get("flavor"))
            field_set = LinearTreeArrays.FIELDS
        else:
            field_set = TreeArrays.FIELDS
        missing = [f for f in field_set if f not in z]
        if missing:
            Log.fatal(
                "Artifact %s is missing tree arrays %s — the file is "
                "truncated or from an incompatible writer; re-pack it",
                origin, missing)
        try:
            fields = {f: z[f] for f in field_set}
        except Exception as e:  # torn member: zipfile CRC error mid-read
            from ..utils.log import LightGBMError

            if isinstance(e, LightGBMError):
                raise
            Log.fatal(
                "Artifact %s fails while reading its tree arrays (%s: %s) "
                "— the file is corrupt; re-pack it", origin,
                type(e).__name__, e)
        if version == QUANT_FORMAT_VERSION:
            if meta.get("leaf_dtype") == "bfloat16":
                fields["leaf_value"] = np.asarray(
                    fields["leaf_value"]).view(_leaf_np_dtype("bfloat16"))
            arrays = QTreeArrays(levels=int(meta.get("levels", 0)), **fields)
        elif version == LINEAR_FORMAT_VERSION:
            arrays = LinearTreeArrays(**fields)
        else:
            arrays = TreeArrays(**fields)
        return cls(arrays, meta)

    # -- checks --------------------------------------------------------
    def validate(self) -> "PredictorArtifact":
        self.arrays.validate()
        required = META_KEYS
        if self.flavor == "quantized":
            required = META_KEYS + QUANT_META_KEYS
        elif self.flavor == "linear":
            required = META_KEYS + LINEAR_META_KEYS
        for key in required:
            if key not in self.meta:
                Log.fatal("Artifact metadata is missing %r", key)
        t = self.arrays.split_feature.shape[0]
        if t != int(self.meta["num_trees"]):
            Log.fatal(
                "Artifact metadata says %s trees but arrays hold %d",
                self.meta["num_trees"], t,
            )
        k = int(self.meta["num_tree_per_iteration"])
        if k <= 0 or t % k != 0:
            Log.fatal(
                "Artifact tree count %d is not a multiple of "
                "num_tree_per_iteration %d", t, k,
            )
        return self

    # -- conveniences --------------------------------------------------
    @property
    def num_class(self) -> int:
        return int(self.meta["num_class"])

    @property
    def num_tree_per_iteration(self) -> int:
        return int(self.meta["num_tree_per_iteration"])

    @property
    def num_features(self) -> int:
        return int(self.meta["num_features"])

    def device_bytes_estimate(self) -> int:
        """Bytes of tree state this artifact will hold resident on
        device once served (after tree-shape padding) — computed from
        shapes alone, so admission control can refuse a model BEFORE
        anything is transferred to the device."""
        import os

        from .compilecache import _TREE_ARG_FIELDS, tree_shape_bucket

        a = self.arrays
        t, m = a.split_feature.shape
        L = a.leaf_value.shape[1]
        bucketed = os.environ.get(
            "LIGHTGBM_TPU_TREE_SHAPE_BUCKETS", "1") != "0"
        if bucketed:
            mb, lb = tree_shape_bucket(m), tree_shape_bucket(L)
        else:
            mb, lb = m, L
        if self.flavor == "quantized":
            from ..ops.qpredict import QTreeArrays

            fields = QTreeArrays.NODE_FIELDS
        elif self.flavor == "linear":
            from .compilecache import _LINEAR_TREE_ARG_FIELDS

            fields = _LINEAR_TREE_ARG_FIELDS
        else:
            fields = _TREE_ARG_FIELDS
        leaf_planes = ("leaf_value", "leaf_const", "leaf_is_linear")
        total = 0
        for f in fields:
            arr = getattr(a, f)
            itemsize = np.dtype(arr.dtype).itemsize
            if arr.ndim == 3:  # (T, L, K) coefficient planes
                kb = (tree_shape_bucket(arr.shape[2]) if bucketed
                      else arr.shape[2])
                total += t * lb * kb * itemsize
            else:
                total += t * (lb if f in leaf_planes else mb) * itemsize
        return int(total)

    def make_objective(self):
        """Rebuild the objective from its model-string form (the same
        ``name key:value ...`` tokens Booster writes/loads)."""
        from ..objective import objective_from_string

        return objective_from_string(self.meta.get("objective", ""))


class PackedPredictor:
    """Device-side serving predictor over a ``PredictorArtifact``:
    bucketed traversal (exact or quantized, following the artifact's
    flavor) + the objective's output conversion, with the same output
    shapes as ``Booster.predict``.

    ``quantized=True`` asks for the int16 rank-quantized path even over
    an exact artifact (it is quantized at construction); ``None``
    follows the artifact flavor.  The ``LIGHTGBM_TPU_QUANT_PREDICT``
    pin overrides both: ``0`` forces exact (a quantized-flavor artifact
    has no exact planes left, so it keeps serving quantized with a loud
    warning), ``1`` forces quantized."""

    def __init__(self, artifact: PredictorArtifact,
                 quantized: Optional[bool] = None):
        from ..ops.qpredict import quant_predict_enabled
        from .compilecache import (BucketedLinearRawPredictor,
                                   BucketedQuantizedPredictor,
                                   BucketedRawPredictor)

        want = (artifact.flavor == "quantized") if quantized is None \
            else bool(quantized)
        use_q = quant_predict_enabled(default=want)
        if use_q and artifact.flavor == "linear":
            Log.warning(
                "Quantized predict was requested but the artifact is "
                "linear-flavor (v3) — the quantized traversal has no "
                "coefficient planes; serving the exact linear path")
            use_q = False
        if use_q and artifact.flavor == "exact":
            artifact = artifact.quantize()
        elif not use_q and artifact.flavor == "quantized":
            Log.warning(
                "Quantized predict is pinned off (LIGHTGBM_TPU_QUANT_"
                "PREDICT=0 or quantized=False) but the artifact is "
                "quantized-flavor, which carries no exact planes — "
                "serving quantized; publish an exact (format_version 1) "
                "artifact to serve the bit-exact path")
            use_q = True
        self.artifact = artifact
        self.quantized = bool(use_q)
        self.objective = artifact.make_objective()
        if self.quantized:
            self.raw = BucketedQuantizedPredictor.from_qtree_arrays(
                artifact.arrays, artifact.num_tree_per_iteration
            )
        elif artifact.flavor == "linear":
            self.raw = BucketedLinearRawPredictor.from_tree_arrays(
                artifact.arrays, artifact.num_tree_per_iteration
            )
        else:
            self.raw = BucketedRawPredictor.from_tree_arrays(
                artifact.arrays, artifact.num_tree_per_iteration
            )

    @property
    def device_bytes(self) -> int:
        """Bytes of stacked tree state resident on device (after shape
        padding) — the admission-control unit for multi-model packing."""
        return int(sum(
            a.nbytes for args in self.raw.class_arrays for a in args
        ))

    @property
    def num_features(self) -> int:
        return self.artifact.num_features

    def warmup(self, max_rows: int, buckets: Optional[List[int]] = None) -> Dict:
        """Precompile the bucket ladder through the FULL predict path —
        traversal AND the objective's output conversion — so a warmed
        predictor answers any covered request size with zero new
        compiles (the PR acceptance contract; raw traversal alone would
        leave the conversion ops compiling per bucket on first use)."""
        import time

        from ..obs import compilewatch, tracer
        from .compilecache import bucket_ladder

        if buckets is None:
            buckets = bucket_ladder(
                max_rows, self.raw.min_bucket, self.raw._row_multiple
            )
        c0 = compilewatch.total_compiles()
        t0 = time.perf_counter()
        with tracer.span("serve_warmup", buckets=len(buckets)):
            for b in buckets:
                self.predict(np.zeros((b, self.num_features)))
        stats = {
            "buckets": list(buckets),
            "compiles": compilewatch.total_compiles() - c0,
            "secs": round(time.perf_counter() - t0, 4),
        }
        tracer.event("serve_warmup_done", **stats)
        return stats

    def predict(self, data: np.ndarray, raw_score: bool = False) -> np.ndarray:
        """(N,) or (N, K) predictions, matching ``Booster.predict``."""
        data = np.asarray(data, np.float64)
        if data.ndim == 1:
            data = data.reshape(1, -1)
        if data.shape[1] < self.num_features:
            Log.fatal(
                "Predict data has %d features but the model needs %d",
                data.shape[1], self.num_features,
            )
        raw = self.raw.predict_raw_scores(data)  # (K, N) f64
        if raw_score:
            return raw[0] if raw.shape[0] == 1 else raw.T
        if self.objective is not None:
            from .compilecache import convert_bucketed

            conv = convert_bucketed(raw, self.objective.convert_output,
                                    self.raw.min_bucket)
        else:
            conv = raw
        return conv[0] if conv.shape[0] == 1 else conv.T
