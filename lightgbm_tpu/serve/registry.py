"""Versioned on-disk model registry — the serving fleet's source of truth.

A serving replica must survive model churn: every retrain publishes a
new ``PredictorArtifact`` and every replica picks it up WITHOUT a
restart (docs/SERVING.md, hot swap).  The registry is a plain directory
any publisher (trainer, CI, ``POST /models``) and any number of replica
processes share:

  registry_dir/
    v00000001.npz     packed PredictorArtifact, immutable once published
    v00000002.npz
    MANIFEST.json     {"entries": {name: {version, crc32, size, ts,
                       num_trees, num_features, ...,
                       dedupe_key?, quarantined?}},
                       "active_version": int|null,
                       "canary_version": int|null,
                       "routes": {route_name: version}}

Named routes (multi-model serving, docs/SERVING.md): ``routes`` maps a
route name (``POST /predict/<route>``) to the version it serves, each
activated/swapped independently of ``active_version`` (the default
route) via ``set_route``/``remove_route``.  Retention protects EVERY
routed version, not just the single active one — N concurrently-active
tenant models must all survive ``keep_last``.

Lifecycle state beyond "active" (the continuous-training factory,
docs/FACTORY.md): ``canary_version`` marks a version under canary
evaluation — retention must not collect the model a canary replica is
serving, however slow the observation window.  ``quarantine(version,
reason)`` records a failed canary verdict on the entry; a quarantined
version is never re-activated by the factory and the most recently
quarantined one survives retention as evidence.  ``publish_bytes``
accepts a ``dedupe_key``: re-publishing the same key returns the
already-claimed version instead of minting a new one, which makes a
crash between publish and the publisher's own state write idempotent
(kill-anywhere restart never double-publishes).

Write protocol (the ckpt/store.py atomic dance, reused literally):
artifact bytes -> tmp + fsync -> hardlink-claim of the next free
``vNNNNNNNN.npz`` name -> directory fsync -> manifest rewritten through
tmp+fsync+rename.  A crash at any point leaves either no trace or an
orphan data file without a manifest entry, which discovery ignores; a
corrupt/truncated artifact fails its manifest CRC at load time and is
refused with a clear error instead of serving garbage.

Watching is poll-based (no inotify dependency): ``watch_token()`` is a
cheap stat of the manifest; replicas poll it and reload on change.
Publishing is cross-process safe: the version name is claimed with an
exclusive hardlink and the manifest read-modify-write runs under a
bounded ``.lock`` file (stale locks from a crashed publisher are broken
after ``LOCK_STALE_S``).
"""

from __future__ import annotations

import json
import os
import re
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..ckpt.store import _atomic_write, _fsync_dir
from ..utils.log import Log
from .artifact import PredictorArtifact

_PREFIX = "v"
_SUFFIX = ".npz"
_MANIFEST = "MANIFEST.json"
_LOCK = ".publish.lock"

LOCK_STALE_S = 30.0
LOCK_WAIT_S = 10.0

# route names land in URLs and manifest keys: path-safe, no dot-prefix
_ROUTE_RE = re.compile(r"^(?!\.)[A-Za-z0-9._\-]{1,64}$")


def _version_name(version: int) -> str:
    return f"{_PREFIX}{int(version):08d}{_SUFFIX}"


def _version_of(name: str) -> Optional[int]:
    base = os.path.basename(name)
    if not (base.startswith(_PREFIX) and base.endswith(_SUFFIX)):
        return None
    try:
        return int(base[len(_PREFIX): -len(_SUFFIX)])
    except ValueError:
        return None


class _PublishLock:
    """Bounded O_EXCL lock file serializing manifest read-modify-write
    across publisher processes.  A lock older than ``LOCK_STALE_S`` is
    from a crashed publisher and is broken with a warning."""

    def __init__(self, directory: str, wait_s: float = LOCK_WAIT_S):
        self.path = os.path.join(directory, _LOCK)
        self.wait_s = float(wait_s)

    def __enter__(self):
        deadline = time.monotonic() + self.wait_s
        while True:
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.write(fd, str(os.getpid()).encode())
                os.close(fd)
                return self
            except FileExistsError:
                try:
                    age = time.time() - os.stat(self.path).st_mtime
                    if age > LOCK_STALE_S:
                        Log.warning(
                            "registry: breaking stale publish lock %s "
                            "(%.0fs old)", self.path, age)
                        os.unlink(self.path)
                        continue
                except OSError:
                    continue  # lock vanished between stat attempts
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"registry publish lock {self.path} held for "
                        f">{self.wait_s}s")
                time.sleep(0.02)

    def __exit__(self, *exc):
        try:
            os.unlink(self.path)
        except OSError:
            pass


class ModelRegistry:
    """Directory of immutable versioned artifacts + atomic CRC'd manifest."""

    def __init__(self, directory: str, keep_last: int = 0):
        self.dir = directory
        # keep_last=0 keeps everything; retention never removes the
        # active version (a replica may still be draining onto it)
        self.keep_last = max(0, int(keep_last))
        os.makedirs(self.dir, exist_ok=True)

    # -- manifest ------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.dir, _MANIFEST)

    def read_manifest(self) -> Dict:
        try:
            with open(self._manifest_path()) as f:
                m = json.load(f)
            if isinstance(m, dict) and isinstance(m.get("entries"), dict):
                m.setdefault("canary_version", None)
                if not isinstance(m.get("routes"), dict):
                    m["routes"] = {}
                return m
        except (OSError, ValueError):
            pass
        return {"entries": {}, "active_version": None, "canary_version": None,
                "routes": {}}

    def _write_manifest(self, manifest: Dict) -> None:
        _atomic_write(self._manifest_path(),
                      json.dumps(manifest, indent=1).encode())

    # -- publish -------------------------------------------------------
    def publish(self, artifact: PredictorArtifact, activate: bool = True,
                dedupe_key: Optional[str] = None) -> int:
        """Publish a validated in-memory artifact; returns its version."""
        import io

        buf = io.BytesIO()
        artifact.save_to_bytes(buf)
        return self.publish_bytes(buf.getvalue(), activate=activate,
                                  dedupe_key=dedupe_key,
                                  _validated_meta=dict(artifact.meta))

    def publish_file(self, path: str, activate: bool = True) -> int:
        with open(path, "rb") as f:
            return self.publish_bytes(f.read(), activate=activate)

    def seed(self, artifact: PredictorArtifact) -> int:
        """Publish ``artifact`` only if the registry is still empty once
        the publish lock is held — N replicas racing to seed a shared
        registry produce exactly one version.  Returns the version now
        active (the seed's, or the one that won the race)."""
        import io

        buf = io.BytesIO()
        artifact.save_to_bytes(buf)
        return self.publish_bytes(buf.getvalue(),
                                  _validated_meta=dict(artifact.meta),
                                  _only_if_empty=True)

    def publish_bytes(self, blob: bytes, activate: bool = True,
                      dedupe_key: Optional[str] = None,
                      _validated_meta: Optional[Dict] = None,
                      _only_if_empty: bool = False) -> int:
        """Publish raw ``.npz`` artifact bytes (the ``POST /models``
        body).  The blob is fully validated through
        ``PredictorArtifact.load`` BEFORE it can claim a version — a
        corrupt upload never enters the manifest.  With ``dedupe_key``
        a key already present in the manifest short-circuits to its
        version: a publisher killed between publish and its own durable
        state write retries idempotently instead of double-publishing."""
        meta = _validated_meta
        if meta is None:
            meta = dict(PredictorArtifact.load_bytes(blob).meta)
        tmp = os.path.join(self.dir, f".publish.tmp.{os.getpid()}")
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        try:
            with _PublishLock(self.dir):
                manifest = self.read_manifest()
                if _only_if_empty and manifest["entries"]:
                    active = manifest.get("active_version")
                    if active is not None:
                        return int(active)
                    return max(int(e["version"])
                               for e in manifest["entries"].values())
                if dedupe_key is not None:
                    for e in manifest["entries"].values():
                        if e.get("dedupe_key") == dedupe_key:
                            return int(e["version"])
                version = self._next_version(manifest)
                path = os.path.join(self.dir, _version_name(version))
                # hardlink-claim: fails loudly if the name exists (a
                # publisher outside the lock), never overwrites
                os.link(tmp, path)
                _fsync_dir(self.dir)
                manifest["entries"][os.path.basename(path)] = {
                    "version": version,
                    "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
                    "size": len(blob),
                    "ts": round(time.time(), 3),
                    "num_trees": int(meta.get("num_trees", 0)),
                    "num_features": int(meta.get("num_features", 0)),
                    "num_class": int(meta.get("num_class", 1)),
                    "objective": str(meta.get("objective", "")),
                }
                if dedupe_key is not None:
                    manifest["entries"][os.path.basename(path)][
                        "dedupe_key"] = str(dedupe_key)
                if activate:
                    manifest["active_version"] = version
                self._gc(manifest)
                self._write_manifest(manifest)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        from ..obs import tracer
        from ..obs.metrics import registry as metrics_registry

        tracer.event("registry.published", version=version,
                     bytes=len(blob), active=bool(activate))
        metrics_registry.counter(
            "lightgbm_tpu_registry_publish_total",
            "artifacts published into the model registry").inc()
        return version

    def _next_version(self, manifest: Dict) -> int:
        top = 0
        for e in manifest["entries"].values():
            top = max(top, int(e["version"]))
        # also scan the directory: an orphan data file from a crashed
        # publisher must not be overwritten by a version-number reuse
        try:
            for name in os.listdir(self.dir):
                v = _version_of(name)
                if v is not None:
                    top = max(top, v)
        except OSError:
            pass
        return top + 1

    def activate(self, version: int) -> None:
        """Point ``active_version`` at an already-published version
        (rollback is just activating an older one)."""
        with _PublishLock(self.dir):
            manifest = self.read_manifest()
            if not any(int(e["version"]) == int(version)
                       for e in manifest["entries"].values()):
                Log.fatal("registry: cannot activate unknown version %s "
                          "(published: %s)", version,
                          sorted(int(e["version"])
                                 for e in manifest["entries"].values()))
            manifest["active_version"] = int(version)
            self._write_manifest(manifest)

    # -- named routes (multi-model serving, docs/SERVING.md) -----------
    def set_route(self, route: str, version: int) -> None:
        """Point route ``route`` (served at ``POST /predict/<route>``)
        at a published version — creating the route, or independently
        hot-swapping it if it exists.  Route names are path-safe
        identifiers; the version must already be published."""
        route = str(route)
        if not _ROUTE_RE.match(route):
            Log.fatal("registry: invalid route name %r (allowed: 1-64 "
                      "chars of [A-Za-z0-9._-], not starting with '.')",
                      route)
        with _PublishLock(self.dir):
            manifest = self.read_manifest()
            if not any(int(e["version"]) == int(version)
                       for e in manifest["entries"].values()):
                Log.fatal("registry: cannot route %r to unknown version %s "
                          "(published: %s)", route, version,
                          sorted(int(e["version"])
                                 for e in manifest["entries"].values()))
            manifest["routes"][route] = int(version)
            self._write_manifest(manifest)
        from ..obs import tracer

        tracer.event("registry.route_set", route=route, version=int(version))

    def remove_route(self, route: str) -> bool:
        """Drop a named route (its version stays published, now subject
        to normal retention).  Returns False when the route did not
        exist."""
        with _PublishLock(self.dir):
            manifest = self.read_manifest()
            existed = manifest["routes"].pop(str(route), None) is not None
            if existed:
                self._write_manifest(manifest)
        if existed:
            from ..obs import tracer

            tracer.event("registry.route_removed", route=str(route))
        return existed

    def routes(self) -> Dict[str, int]:
        """{route_name: version} for every named route."""
        return {str(r): int(v)
                for r, v in self.read_manifest()["routes"].items()}

    def route_version(self, route: str) -> Optional[int]:
        v = self.read_manifest()["routes"].get(str(route))
        return int(v) if v is not None else None

    # -- canary / quarantine lifecycle (docs/FACTORY.md) ---------------
    def set_canary(self, version: Optional[int]) -> None:
        """Mark ``version`` as under canary evaluation (``None`` clears).
        A canary version is retention-protected for the whole
        observation window — GC must never collect the model the canary
        replica is pinned to."""
        with _PublishLock(self.dir):
            manifest = self.read_manifest()
            if version is not None and not any(
                    int(e["version"]) == int(version)
                    for e in manifest["entries"].values()):
                Log.fatal("registry: cannot canary unknown version %s "
                          "(published: %s)", version,
                          sorted(int(e["version"])
                                 for e in manifest["entries"].values()))
            manifest["canary_version"] = (
                int(version) if version is not None else None)
            self._write_manifest(manifest)

    def clear_canary(self) -> None:
        self.set_canary(None)

    def canary_version(self) -> Optional[int]:
        v = self.read_manifest().get("canary_version")
        return int(v) if v is not None else None

    def quarantine(self, version: int, reason: str) -> None:
        """Record a failed canary verdict on a published version.  A
        quarantined version keeps its artifact (the most recent one is
        retention-protected as evidence) but the factory never
        re-activates it; the reason string is the audit trail."""
        with _PublishLock(self.dir):
            manifest = self.read_manifest()
            entry = None
            for e in manifest["entries"].values():
                if int(e["version"]) == int(version):
                    entry = e
                    break
            if entry is None:
                Log.fatal("registry: cannot quarantine unknown version %s "
                          "(published: %s)", version,
                          sorted(int(e["version"])
                                 for e in manifest["entries"].values()))
            entry["quarantined"] = str(reason)
            if manifest.get("canary_version") == int(version):
                manifest["canary_version"] = None
            self._write_manifest(manifest)
        from ..obs import tracer

        tracer.event("registry.quarantined", version=int(version),
                     reason=str(reason))

    def quarantined(self) -> Dict[int, str]:
        """{version: reason} for every quarantined entry."""
        return {int(e["version"]): str(e["quarantined"])
                for e in self.read_manifest()["entries"].values()
                if e.get("quarantined")}

    def _gc(self, manifest: Dict) -> None:
        if self.keep_last <= 0:
            return
        entries = manifest["entries"]
        # retention protects everything a process may still be serving
        # or a human may still need: the active version (replicas drain
        # onto it), EVERY routed version (multi-model serving keeps N
        # versions concurrently active — collecting any routed active
        # would 404 a live route on its next replica load), the canary
        # version (a slow observation window must not lose the model
        # under evaluation), and the most recently quarantined version
        # (the rollback evidence)
        protected = {manifest.get("active_version"),
                     manifest.get("canary_version")}
        protected.update(int(v) for v in manifest.get("routes", {}).values())
        quarantined = [int(e["version"]) for e in entries.values()
                       if e.get("quarantined")]
        if quarantined:
            protected.add(max(quarantined))
        versions = sorted((int(e["version"]), name)
                          for name, e in entries.items())
        while len(versions) > self.keep_last:
            v, name = versions.pop(0)
            if v in protected:
                continue
            entries.pop(name, None)
            try:
                os.unlink(os.path.join(self.dir, name))
            except OSError:
                pass

    # -- read side -----------------------------------------------------
    def list_models(self) -> List[Dict]:
        """Manifest entries, oldest first, with lifecycle flags set."""
        manifest = self.read_manifest()
        active = manifest.get("active_version")
        canary = manifest.get("canary_version")
        routes = manifest.get("routes", {})
        out = []
        for name, e in sorted(manifest["entries"].items(),
                              key=lambda kv: int(kv[1]["version"])):
            row = dict(e)
            row["name"] = name
            row["active"] = int(e["version"]) == active if active else False
            row["canary"] = (int(e["version"]) == canary
                             if canary is not None else False)
            row["quarantined"] = str(e["quarantined"]) \
                if e.get("quarantined") else None
            row["routes"] = sorted(r for r, v in routes.items()
                                   if int(v) == int(e["version"]))
            out.append(row)
        return out

    def active_version(self) -> Optional[int]:
        v = self.read_manifest().get("active_version")
        return int(v) if v is not None else None

    def latest_version(self) -> Optional[int]:
        versions = [int(e["version"])
                    for e in self.read_manifest()["entries"].values()]
        return max(versions) if versions else None

    def load(self, version: int) -> PredictorArtifact:
        """Load + CRC-verify a published version.  A corrupt or
        truncated file is refused with the manifest evidence — never
        silently served."""
        manifest = self.read_manifest()
        entry = None
        for name, e in manifest["entries"].items():
            if int(e["version"]) == int(version):
                entry = (name, e)
                break
        if entry is None:
            Log.fatal("registry: version %s is not in %s (published: %s)",
                      version, self.dir,
                      sorted(int(e["version"])
                             for e in manifest["entries"].values()))
        name, e = entry
        path = os.path.join(self.dir, name)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as ex:
            Log.fatal("registry: cannot read %s: %s", path, ex)
        if len(blob) != int(e.get("size", -1)) or (
                zlib.crc32(blob) & 0xFFFFFFFF) != int(e.get("crc32", -1)):
            Log.fatal(
                "registry: %s fails its manifest CRC/size check "
                "(%d bytes vs %s recorded) — the artifact is corrupt or "
                "torn; republish it", path, len(blob), e.get("size"))
        return PredictorArtifact.load_bytes(blob)

    def load_active(self) -> Optional[Tuple[int, PredictorArtifact]]:
        v = self.active_version()
        if v is None:
            return None
        return v, self.load(v)

    # -- watch ---------------------------------------------------------
    def watch_token(self) -> Tuple:
        """Cheap change token: manifest identity (size + mtime_ns) plus
        the active version and the route table.  Polling replicas
        reload when it changes — no inotify, works on any filesystem
        including network mounts."""
        try:
            st = os.stat(self._manifest_path())
            ident = (int(st.st_size), int(st.st_mtime_ns))
        except OSError:
            ident = (0, 0)
        m = self.read_manifest()
        active = m.get("active_version")
        return ident + (
            int(active) if active is not None else None,
            tuple(sorted((str(r), int(v)) for r, v in m["routes"].items())),
        )
