"""Tree-growth strategy seams — the composable trainer core.

Every learner (serial grow_tree, ShardedLearner, HostParallelLearner,
OocTrainer, DistributedOocTrainer) consumes one :class:`TreeStrategy`
instead of re-implementing gain math, leaf fitting, histogram
accumulation and export plumbing inline.  A strategy is a NamedTuple of
NamedTuples so it is hashable and can ride ``GrowParams`` (a static jit
argument): swapping a strategy recompiles the growth program, it never
retraces per call.

The four seams (docs/TREES.md):

``SplitGainStrategy``
    How candidate splits are scored and constrained.  Carries the
    per-inner-feature monotone direction vector (+1 / 0 / -1); the
    default (all zero) compiles to the exact pre-strategy graph.
``LeafFitStrategy``
    How leaf models are fitted after growth: ``const`` (the classic
    output) or ``linear`` (per-leaf ridge least-squares over the leaf's
    path features, tree/linear.py).
``HistAccumStrategy``
    How histograms accumulate: f32 or stochastically-rounded integer
    levels with exact int32 accumulation (quantized training).
``StateExportStrategy``
    What leaves the trainer: leaf-model kind for checkpoints, model
    text, and the serving-artifact format version.

Extending: add a field to the relevant seam, default it to the current
behaviour, branch where the seam is consumed, and every learner picks
the capability up through ``GrowParams.strategy`` — one file, not five
parallel edits.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple


class SplitGainStrategy(NamedTuple):
    """Split scoring: monotone direction per INNER feature (+1 increasing,
    0 unconstrained, -1 decreasing).  Empty tuple = fully unconstrained
    (the compiled graph is byte-identical to pre-strategy code)."""

    monotone: Tuple[int, ...] = ()

    @property
    def constrained(self) -> bool:
        return any(c != 0 for c in self.monotone)


class LeafFitStrategy(NamedTuple):
    """Leaf-model fit: ``const`` or ``linear`` (+ the ridge strength)."""

    kind: str = "const"
    linear_lambda: float = 0.0

    @property
    def linear(self) -> bool:
        return self.kind == "linear"


class HistAccumStrategy(NamedTuple):
    """Histogram accumulation: f32, or quantized int16 gradient levels
    with exact int32 accumulation (ops/qhist.py)."""

    quantized: bool = False
    quant_bits: int = 0  # 0 = library default (ops.qhist.QUANT_BITS)
    quant_seed: int = 0

    def resolved_bits(self) -> int:
        if self.quant_bits:
            return self.quant_bits
        from ..ops.qhist import QUANT_BITS

        return QUANT_BITS


class StateExportStrategy(NamedTuple):
    """Export surface: what the fitted leaves look like downstream.

    ``leaf_model`` feeds model text / checkpoints; the serving artifact
    picks its format version off it (v3 carries coefficient planes,
    serve/artifact.py)."""

    leaf_model: str = "const"


class TreeStrategy(NamedTuple):
    split_gain: SplitGainStrategy = SplitGainStrategy()
    leaf_fit: LeafFitStrategy = LeafFitStrategy()
    hist_accum: HistAccumStrategy = HistAccumStrategy()
    state_export: StateExportStrategy = StateExportStrategy()

    @classmethod
    def from_config(cls, config, train_set=None) -> "TreeStrategy":
        """Build the strategy a Config implies.  ``train_set`` (when
        given) maps real-feature monotone constraints onto INNER feature
        order and zeroes categorical columns (monotonicity is undefined
        for one-vs-rest splits)."""
        monotone: Tuple[int, ...] = ()
        raw = getattr(config, "monotone_constraints", "") or ""
        if str(raw).strip() and train_set is not None:
            monotone = _inner_monotone(config, train_set)
        leaf = LeafFitStrategy(
            kind="linear" if getattr(config, "linear_tree", False)
            else "const",
            linear_lambda=float(getattr(config, "linear_lambda", 0.0)),
        )
        hist = HistAccumStrategy(
            quantized=bool(getattr(config, "quantized_training", False)),
            quant_bits=int(getattr(config, "quantized_grad_bits", 0) or 0),
            quant_seed=int(getattr(config, "seed", 0)),
        )
        return cls(
            split_gain=SplitGainStrategy(monotone=monotone),
            leaf_fit=leaf,
            hist_accum=hist,
            state_export=StateExportStrategy(leaf_model=leaf.kind),
        )


DEFAULT_STRATEGY = TreeStrategy()


def parse_monotone_constraints(value, num_features: int,
                               feature_names=None) -> Tuple[int, ...]:
    """Parse ``monotone_constraints`` into a length-``num_features``
    tuple over REAL feature indices.

    Accepted forms (LightGBM's surface):
      * comma list: ``"+1,0,-1"`` — one entry per feature, length must
        match ``num_features``;
      * dict: ``{"0": 1, "f3": -1}`` — keys are feature indices or
        names from ``feature_names``; unnamed features default to 0.
    """
    from ..utils.log import Log

    def _dir(v, what):
        try:
            c = int(str(v).strip() or 0)
        except ValueError:
            Log.fatal(
                "monotone_constraints: %s is not a direction "
                "(+1 / 0 / -1)", what)
        if c not in (-1, 0, 1):
            Log.fatal(
                "monotone_constraints: direction %d for %s is out of "
                "range; use +1 (increasing), 0 (none) or -1 "
                "(decreasing)", c, what)
        return c

    if isinstance(value, dict):
        out = [0] * num_features
        names = {str(n): i for i, n in enumerate(feature_names or [])}
        for key, v in value.items():
            k = str(key)
            if k in names:
                idx = names[k]
            else:
                try:
                    idx = int(k)
                except ValueError:
                    Log.fatal(
                        "monotone_constraints: unknown feature %r "
                        "(not an index and not one of the dataset's "
                        "feature names)", key)
                if not 0 <= idx < num_features:
                    Log.fatal(
                        "monotone_constraints: feature index %d out of "
                        "range for %d features", idx, num_features)
            out[idx] = _dir(v, f"feature {key!r}")
        return tuple(out)

    parts = [p for p in str(value).split(",")]
    if len(parts) == 1 and not parts[0].strip():
        return tuple([0] * num_features)
    if len(parts) != num_features:
        Log.fatal(
            "monotone_constraints has %d entries but the dataset has "
            "%d features; pass one +1/0/-1 per feature (comma list) or "
            "a {feature: direction} dict", len(parts), num_features)
    return tuple(_dir(p, f"entry {i}") for i, p in enumerate(parts))


def _inner_monotone(config, train_set) -> Tuple[int, ...]:
    """Map the config's REAL-feature constraint vector onto the
    dataset's INNER feature order, zeroing categorical columns
    (monotonicity is undefined for one-vs-rest splits).  The EFB-bundled
    matrix only feeds ptrainer, which declines constrained configs, so
    inner order here is the unbundled column order."""
    from ..io.binning import CATEGORICAL
    from ..utils.log import Log

    raw = config.monotone_constraints
    names = getattr(train_set, "feature_names", None)
    num_real = int(getattr(train_set, "num_total_features",
                           train_set.num_features))
    real = parse_monotone_constraints(raw, num_real, names)
    if not any(real):
        return ()
    inner = []
    seen_real = set()
    for i in range(train_set.num_features):
        r = int(train_set.inner_to_real_feature(i))
        c = 0 if r < 0 else real[r]
        if train_set.bin_mappers[i].bin_type == CATEGORICAL and c != 0:
            Log.warning(
                "monotone_constraints: feature %d is categorical; "
                "monotonicity is undefined for one-vs-rest splits — "
                "constraint ignored.", r)
            c = 0
        if r >= 0:
            seen_real.add(r)
        inner.append(c)
    dropped = [r for r, c in enumerate(real) if c != 0 and r not in seen_real]
    if dropped:
        Log.warning(
            "monotone_constraints: features %s were pruned or bundled "
            "away during binning; their constraints do not apply.",
            dropped)
    return tuple(inner)
