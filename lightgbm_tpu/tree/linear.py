"""Piecewise-linear leaves — the LeafFit strategy plug-in.

"Gradient Boosting With Piece-Wise Linear Regression Trees" (1802.05640,
PAPERS.md): after a tree's structure is grown with the classic
constant-leaf gain scan (exactly like the reference's linear_tree), each
leaf gets a tiny ridge least-squares model over the numerical features
on its root path.  Per boosting iteration that is L independent
(k+1)x(k+1) normal-equation solves — batched here as ONE
``(L, k+1, k+1)`` Cholesky/solve, which is MXU-shaped work instead of L
scalar loops.

The second-order objective restricted to leaf l is

    min_w  sum_i  h_i/2 (w·x~_i)^2 + g_i (w·x~_i)  + reg(w)

with x~ = (1, x_1..x_k), giving  (A + D) w = -b  where
A = sum h_i x~ x~^T, b = sum g_i x~ (f32 accumulate, row-block
sequential adds so results do not depend on device tiling), and D adds
``linear_lambda`` on the slope diagonal and ``lambda_l2`` on the
intercept (so a k=0 leaf solves to the classic constant output with
lambda_l1=0).

Drift contract (docs/TREES.md): fits and binned score updates evaluate
features at BIN-REPRESENTATIVE values (``build_value_lut``), while raw
serving evaluates at raw values.  Training is self-consistent — the
same LUT feeds fit, train-score and valid-score paths — and the
fit-vs-serve drift is bounded by bin width exactly like threshold
quantization itself.

Degenerate leaves (no numerical path features, fewer selected rows than
coefficients, non-PD normal matrix) fall back to the grower's constant
output; ``fit_linear_leaves`` returns a per-leaf validity mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# rows per accumulation block: bounds the (R, k+1, k+1) outer-product
# intermediate while keeping the block-sequential float add order
FIT_ROW_BLOCK = 65536


def build_value_lut(dataset, num_bins: int) -> np.ndarray:
    """(F, num_bins) f32 bin-representative values per INNER feature.

    Numerical bins are represented by their upper bound (the same value
    ``Tree.from_grow_result`` records as the split threshold); the last
    bin's +inf bound is replaced by the largest finite bound so fits
    stay finite.  Categorical columns are zeroed — they never enter a
    linear fit (leaf_path_features drops them)."""
    from ..io.binning import CATEGORICAL

    f = dataset.num_features
    lut = np.zeros((f, num_bins), np.float32)
    for i in range(f):
        m = dataset.bin_mappers[i]
        if m.bin_type == CATEGORICAL:
            continue
        nb = int(m.num_bin)
        ub = np.asarray(m.bin_upper_bound, np.float64)
        vals = ub[:nb].copy()
        if nb >= 2 and not np.isfinite(vals[nb - 1]):
            vals[nb - 1] = vals[nb - 2]
        vals = np.where(np.isfinite(vals), vals, 0.0)
        lut[i, :nb] = vals.astype(np.float32)
        if nb < num_bins:
            lut[i, nb:] = lut[i, nb - 1]
    return lut


def leaf_path_features(gr, is_categorical) -> list:
    """Per-leaf tuples of INNER numerical features on the leaf's root
    path, reconstructed host-side from the GrowResult split records
    (left child keeps the split leaf's index, right child is s+1 —
    the same indexing model/tree.py replays)."""
    num_splits = int(gr.num_splits)
    rec_leaf = np.asarray(gr.rec_leaf)
    rec_feat = np.asarray(gr.rec_feat)
    is_cat = np.asarray(is_categorical)
    feats = {0: ()}
    for s in range(num_splits):
        bl = int(rec_leaf[s])
        f = int(rec_feat[s])
        path = feats[bl]
        if not is_cat[f] and f not in path:
            path = path + (f,)
        feats[bl] = path
        feats[s + 1] = path
    return [feats[i] for i in range(num_splits + 1)]


def pack_path_features(paths, num_leaves: int, k_max: int = 0):
    """(L, k) int32 feature-index matrix (0-padded) + (L, k) f32
    validity mask from per-leaf path tuples.  ``k_max`` pads wider when
    given (so OOC chunk folds reuse one compiled shape)."""
    k = max((len(p) for p in paths), default=0)
    k = max(k, k_max, 1)
    idx = np.zeros((num_leaves, k), np.int32)
    valid = np.zeros((num_leaves, k), np.float32)
    for i, p in enumerate(paths[:num_leaves]):
        idx[i, : len(p)] = p
        valid[i, : len(p)] = 1.0
    return idx, valid


@functools.partial(jax.jit, static_argnames=("num_leaves", "row_block"))
def linear_fit_stats(bins, grad, hess, select, leaf_id, feat_idx,
                     feat_valid, value_lut, num_leaves: int,
                     row_block: int = FIT_ROW_BLOCK):
    """Accumulate the per-leaf normal equations: (L, k+1, k+1) A and
    (L, k+1) b over the full resident matrix, in row-block order."""
    n, f = bins.shape
    rb = min(row_block, max(int(n), 1))
    nblocks = -(-n // rb)
    pad = nblocks * rb - n
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        grad = jnp.pad(grad, (0, pad))
        hess = jnp.pad(hess, (0, pad))
        select = jnp.pad(select, (0, pad))
        leaf_id = jnp.pad(leaf_id, (0, pad))

    def body(i, carry):
        a, bv = carry
        s = i * rb
        bb = jax.lax.dynamic_slice(bins, (s, 0), (rb, f))
        g = jax.lax.dynamic_slice(grad, (s,), (rb,))
        h = jax.lax.dynamic_slice(hess, (s,), (rb,))
        sel = jax.lax.dynamic_slice(select, (s,), (rb,))
        lid = jax.lax.dynamic_slice(leaf_id, (s,), (rb,))
        return _fold_block(a, bv, bb, g, h, sel, lid, feat_idx,
                           feat_valid, value_lut)

    k1 = feat_idx.shape[1] + 1
    a0 = jnp.zeros((num_leaves, k1, k1), jnp.float32)
    b0 = jnp.zeros((num_leaves, k1), jnp.float32)
    return jax.lax.fori_loop(0, nblocks, body, (a0, b0))


def _fold_block(a, bv, bins_blk, g, h, sel, lid, feat_idx, feat_valid,
                value_lut):
    """One row block's contribution to (A, b) — shared by the resident
    fit above and the streamed OOC fold (linear_stats_chunk)."""
    rb = bins_blk.shape[0]
    fi = feat_idx[lid]  # (R, k)
    fv = feat_valid[lid]  # (R, k)
    bcol = jnp.take_along_axis(bins_blk.astype(jnp.int32), fi, axis=1)
    x = value_lut[fi, bcol] * fv  # (R, k), invalid slots -> 0
    xt = jnp.concatenate([jnp.ones((rb, 1), jnp.float32), x], axis=1)
    hw = h * sel
    gw = g * sel
    a = a.at[lid].add(hw[:, None, None] * xt[:, :, None] * xt[:, None, :])
    bv = bv.at[lid].add(gw[:, None] * xt)
    return a, bv


@functools.partial(jax.jit, donate_argnums=(0, 1))
def linear_stats_chunk(a, bv, bins_chunk, grad, hess, select, leaf_id,
                       start, feat_idx, feat_valid, value_lut):
    """Streamed counterpart of one ``linear_fit_stats`` block: fold one
    out-of-core chunk's rows into the running (A, b) carries (the
    ChunkFolder seam, boosting/ooc.py)."""
    c = bins_chunk.shape[0]
    g = jax.lax.dynamic_slice(grad, (start,), (c,))
    h = jax.lax.dynamic_slice(hess, (start,), (c,))
    sel = jax.lax.dynamic_slice(select, (start,), (c,))
    lid = jax.lax.dynamic_slice(leaf_id, (start,), (c,))
    return _fold_block(a, bv, bins_chunk, g, h, sel, lid, feat_idx,
                       feat_valid, value_lut)


@jax.jit
def solve_linear_leaves(a, bv, feat_valid, leaf_cnt, linear_lambda,
                        lambda_l2):
    """Batched ridge solve of (A + D) w = -b per leaf via ONE Cholesky.

    D = linear_lambda on valid slope slots, lambda_l2 on the intercept,
    and 1.0 on PADDED slots (their A rows/cols are zero; the unit
    diagonal makes the factor well-defined and solves them to exactly
    w_j = 0).  Returns (w, ok): leaves with a non-finite factor (non-PD
    A), no valid features, or fewer selected rows than coefficients are
    flagged for constant fallback."""
    l_, k1 = bv.shape
    kv = jnp.sum(feat_valid, axis=1)  # (L,) valid slope count
    diag = jnp.concatenate(
        [jnp.full((l_, 1), lambda_l2, jnp.float32),
         jnp.where(feat_valid > 0, jnp.float32(linear_lambda), 1.0)],
        axis=1)
    areg = a + diag[:, :, None] * jnp.eye(k1, dtype=jnp.float32)[None]
    chol = jnp.linalg.cholesky(areg)  # NaN rows when not PD
    y = jax.scipy.linalg.solve_triangular(chol, -bv[..., None], lower=True)
    w = jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(chol, -1, -2), y, lower=False)[..., 0]
    ok = (jnp.all(jnp.isfinite(w), axis=1)
          & (kv > 0)
          & (leaf_cnt > kv + 1.0))
    return jnp.where(ok[:, None], w, 0.0), ok


@jax.jit
def linear_leaf_scores(bins, leaf_id, feat_idx, feat_valid, coeff, const,
                       fallback, is_lin, value_lut):
    """(N,) per-row outputs of ONE freshly-grown linear tree on binned
    rows: the linear model where the leaf has one, the constant
    fallback otherwise (the train-score counterpart of
    add_leaf_outputs)."""
    fi = feat_idx[leaf_id]
    fv = feat_valid[leaf_id]
    bcol = jnp.take_along_axis(bins.astype(jnp.int32), fi, axis=1)
    x = value_lut[fi, bcol] * fv
    lin = const[leaf_id] + jnp.sum(coeff[leaf_id] * x, axis=1)
    return jnp.where(is_lin[leaf_id], lin, fallback[leaf_id])


@jax.jit
def linear_scores_chunk(bins_chunk, leaf_id, start, feat_idx, feat_valid,
                        coeff, const, fallback, is_lin, value_lut):
    """One chunk's (C,) outputs of a freshly-grown linear tree — the
    streamed counterpart of ``linear_leaf_scores`` (ChunkFolder seam)."""
    c = bins_chunk.shape[0]
    lid = jax.lax.dynamic_slice(leaf_id, (start,), (c,))
    fi = feat_idx[lid]
    fv = feat_valid[lid]
    bcol = jnp.take_along_axis(bins_chunk.astype(jnp.int32), fi, axis=1)
    x = value_lut[fi, bcol] * fv
    lin = const[lid] + jnp.sum(coeff[lid] * x, axis=1)
    return jnp.where(is_lin[lid], lin, fallback[lid])


def _leaves_one_tree(bins, feat, thr_bin, zero_bin, dbz, is_cat, left,
                     right):
    from ..ops.predict import _traverse_one_tree_binned

    return _traverse_one_tree_binned(bins, feat, thr_bin, zero_bin, dbz,
                                     is_cat, left, right)


@jax.jit
def predict_linear_binned(bins, split_feature, threshold_bin, zero_bin,
                          default_bin_for_zero, is_categorical, left_child,
                          right_child, leaf_value, leaf_feat,
                          leaf_feat_valid, leaf_coeff, leaf_const,
                          leaf_is_linear, value_lut):
    """Sum of stacked-tree outputs on binned rows where leaves may carry
    linear models: (T, L[, k]) planes ride alongside the classic node
    arrays; constant trees pass leaf_is_linear all-False and reproduce
    ``predict_binned`` values exactly (same traversal, same gather)."""
    leaves = jax.vmap(
        _leaves_one_tree, in_axes=(None, 0, 0, 0, 0, 0, 0, 0)
    )(bins, split_feature, threshold_bin, zero_bin, default_bin_for_zero,
      is_categorical, left_child, right_child)  # (T, N)

    def one_tree(lv, lval_t, lf, lvalid, lc, lconst, lisl):
        fi = lf[lv]  # (N, k)
        fvalid = lvalid[lv]
        bcol = jnp.take_along_axis(bins.astype(jnp.int32), fi, axis=1)
        x = value_lut[fi, bcol] * fvalid
        lin = lconst[lv] + jnp.sum(lc[lv] * x, axis=1)
        return jnp.where(lisl[lv], lin, lval_t[lv])

    vals = jax.vmap(one_tree)(leaves, leaf_value, leaf_feat,
                              leaf_feat_valid, leaf_coeff, leaf_const,
                              leaf_is_linear)
    return jnp.sum(vals, axis=0)
