"""Composable trainer core: pluggable tree-growth strategies.

``strategy.py`` defines the four seams (SplitGain / LeafFit / HistAccum
/ StateExport) every learner consumes; ``linear.py`` is the
piecewise-linear leaf plug-in (batched per-leaf ridge fits).  See
docs/TREES.md.
"""

from .strategy import (
    DEFAULT_STRATEGY,
    HistAccumStrategy,
    LeafFitStrategy,
    SplitGainStrategy,
    StateExportStrategy,
    TreeStrategy,
    parse_monotone_constraints,
)

__all__ = [
    "DEFAULT_STRATEGY",
    "HistAccumStrategy",
    "LeafFitStrategy",
    "SplitGainStrategy",
    "StateExportStrategy",
    "TreeStrategy",
    "parse_monotone_constraints",
]
