"""CLI application — counterpart of src/application/application.cpp +
src/main.cpp: ``python -m lightgbm_tpu task=train config=train.conf``
accepts the reference's key=value argv and .conf files unmodified
(LoadParameters, application.cpp:48-104).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, List

import numpy as np

from .basic import Booster, Dataset
from .config import PARAM_ALIASES, Config, canonicalize_params
from .utils.log import Log

# Exit codes (docs/ROBUSTNESS.md).  sysexits-flavored so supervisors can
# tell a retryable infrastructure death from a config/data error:
# EX_TEMPFAIL (75) = a peer died; restarting the job auto-resumes from
# the last checkpoint.  EX_IOERR (74) = a collective or the distributed
# bootstrap timed out with peers apparently alive (lost collective,
# dead tunnel) — also retryable, but worth alerting on.
EXIT_PEER_FAILURE = 75
EXIT_NET_TIMEOUT = 74


def parse_argv(argv: List[str]) -> Dict[str, str]:
    """key=value argv parsing (LoadParameters, application.cpp:48-61)."""
    params: Dict[str, str] = {}
    for arg in argv:
        if "=" in arg:
            key, _, value = arg.partition("=")
            key = key.strip().strip('"').strip("'")
            value = value.strip().strip('"').strip("'")
            if key:
                params[key] = value
        else:
            Log.warning("Unknown parameter in command line: %s", arg)
    return params


def parse_config_file(path: str) -> Dict[str, str]:
    """.conf parsing with '#' comments (application.cpp:66-98)."""
    params: Dict[str, str] = {}
    if not os.path.exists(path):
        Log.warning("Config file %s doesn't exist, will ignore", path)
        return params
    with open(path) as f:
        for line in f:
            if "#" in line:
                line = line[: line.index("#")]
            line = line.strip()
            if not line:
                continue
            if "=" in line:
                key, _, value = line.partition("=")
                key = key.strip().strip('"').strip("'")
                value = value.strip().strip('"').strip("'")
                if key:
                    params[key] = value
            else:
                Log.warning("Unknown parameter in config file: %s", line)
    return params


def load_all_params(argv: List[str]) -> Dict[str, str]:
    params = parse_argv(argv)
    # resolve config/config_file alias before reading the file
    cfg_path = params.get("config_file") or params.get("config")
    if cfg_path:
        file_params = parse_config_file(cfg_path)
        for key, value in file_params.items():
            # command line has higher priority (application.cpp:87-89)
            canon = PARAM_ALIASES.get(key, key)
            if key not in params and canon not in params and not any(
                PARAM_ALIASES.get(k, k) == canon for k in params
            ):
                params[key] = value
    params.pop("config", None)
    params.pop("config_file", None)
    return params


def run_train(config: Config, params: Dict[str, str]) -> None:
    """InitTrain + Train (application.cpp:188-250).

    Fault tolerance (docs/CHECKPOINT.md): ``snapshot_freq`` now writes
    REAL training-state checkpoints through ``ckpt/`` (the reference's
    periodic model-text dump is still emitted alongside for reference
    compat), and ``task=train`` auto-resumes an interrupted run from the
    latest valid checkpoint in ``output_model``'s directory — the
    resumed run is bit-identical to one that never died.  SIGTERM
    (preemption) flushes a checkpoint at the next iteration boundary and
    exits cleanly."""
    if not config.data:
        Log.fatal("No training data, application quit")
    train_ds = Dataset(config.data, params=dict(params))
    booster = Booster(params=dict(params), train_set=train_ds)
    for i, vpath in enumerate(config.valid_data):
        name = os.path.basename(vpath)
        booster.add_valid(train_ds.create_valid(vpath), name)
    if config.is_save_binary_file:
        train_ds.save_binary(config.data + ".bin")

    from .ckpt import CheckpointManager, PreemptionExit
    from .obs import flight
    from .parallel.net import NetError

    # live-run forensics: SIGUSR1 flushes the flight-recorder ring to
    # <trace>.crash.jsonl without disturbing training (docs/OBSERVABILITY.md)
    flight.install_signal_handler()

    b = booster.boosting
    num_iters = config.num_iterations
    ckpt_freq = config.checkpoint_freq or config.snapshot_freq
    resume = str(config.checkpoint_resume).lower()
    mgr = None
    start_iter = 0
    if ckpt_freq > 0 or resume == "force":
        ckpt_dir = config.checkpoint_dir or (
            os.path.dirname(os.path.abspath(config.output_model))
        )
        mgr = CheckpointManager(ckpt_dir, freq=max(ckpt_freq, 0),
                                keep_last=config.checkpoint_keep)
        mgr.install_signal_handlers()
        if resume not in ("false", "0", "none", ""):
            state = mgr.try_restore(
                booster, require=(resume == "force"),
                ignore_complete=(resume == "force"),
            )
            if state is not None:
                start_iter = state.iteration
                Log.info("Resuming training from checkpoint at iteration %d",
                         start_iter)

    # LIGHTGBM_TPU_XPROF=<dir>: bounded device-profiler capture across a
    # few steady-state iterations (utils/profiling.XprofCapture) — the
    # ROADMAP recapture sweep needs only the env var, no code
    from .utils.profiling import maybe_xprof_capture

    xprof = maybe_xprof_capture()
    Log.info("Started training...")
    try:
        for it in range(start_iter, num_iters):
            start = time.time()
            if xprof is not None:
                xprof.on_iter_start()
            finished = b.train_one_iter(is_eval=True)
            if xprof is not None:
                xprof.on_iter_end()
            Log.info("%f seconds elapsed, finished iteration %d",
                     time.time() - start, it + 1)
            if config.snapshot_freq > 0 and (it + 1) % config.snapshot_freq == 0:
                # reference-compat model text alongside the real checkpoint
                snap = f"{config.output_model}.snapshot_iter_{it + 1}"
                b.save_model_to_file(snap)
                Log.info("Saved snapshot to %s", snap)
            if mgr is not None:
                mgr.maybe_save(booster)
            if finished:
                Log.info("Early stopping at iteration %d", it + 1)
                break
    except PreemptionExit as px:
        mgr.flush()
        Log.warning(
            "Training preempted: checkpoint flushed at iteration %d; "
            "rerun task=train (or `python -m lightgbm_tpu resume`) to "
            "continue bit-identically", px.step,
        )
        return
    except NetError:
        # peer failure / collective timeout: keep the last completed
        # checkpoint durable and let main() map the typed error to a
        # retryable exit code (docs/ROBUSTNESS.md cooperative abort)
        if mgr is not None:
            mgr.flush()
        raise
    finally:
        if xprof is not None:
            xprof.close()
    if mgr is not None:
        mgr.mark_complete(booster)
        mgr.close()
    b.save_model_to_file(config.output_model)
    Log.info("Finished training, model saved to %s", config.output_model)
    _dump_metrics_if_requested()


def _dump_metrics_if_requested() -> None:
    """End-of-train Prometheus dump: LIGHTGBM_TPU_METRICS=path writes
    the registry (compile accounting + every mirrored trace counter and
    gauge) in the exposition text format — the offline twin of the
    serve front end's live ``GET /metrics``."""
    path = os.environ.get("LIGHTGBM_TPU_METRICS", "").strip()
    if not path:
        return
    from .obs.metrics import registry

    try:
        registry.dump(path)
        Log.info("Metrics dumped to %s", path)
    except OSError as e:
        Log.warning("Could not dump metrics to %s: %s", path, e)


def run_ingest(config: Config, params: Dict[str, str]) -> None:
    """task=ingest (TPU extension): stream a text file through the
    out-of-core pipeline (data/ingest.py) into the binary dataset cache
    ``<data>.bin`` — the raw float matrix is never materialized, so
    arbitrarily large files prep on a bounded-memory host.  Training
    then loads the cache (DatasetLoader::LoadFromBinFile path)."""
    import json

    from .data.ingest import stream_dataset
    from .obs import tracer

    if not config.data:
        Log.fatal("No data for ingest, application quit")
    tracer.refresh_from_env()
    ds = stream_dataset(config.data, config)
    out = config.data + ".bin"
    ds.save_binary(out, source_path=config.data)
    report = dict(getattr(ds, "ingest_report", {}))
    report["output"] = out
    Log.info("Finished ingest: %s", json.dumps(report))


def run_convert_model(config: Config, params: Dict[str, str]) -> None:
    """task=convert_model (application.cpp:268-273): emit the standalone
    C++ if-else predictor (convert_model.py <- GBDT::ModelToIfElse)."""
    from .basic import Booster
    from .convert_model import model_to_cpp

    if not config.input_model:
        Log.fatal("No model file for convert_model, application quit")
    if config.convert_model_language not in ("", "cpp"):
        Log.fatal("Unsupported convert_model_language %s (only cpp)",
                  config.convert_model_language)
    booster = Booster(model_file=config.input_model)
    out = config.convert_model or "gbdt_prediction.cpp"
    with open(out, "w") as f:
        f.write(model_to_cpp(booster.boosting))
    Log.info("Finished converting model to C++ code, saved to %s", out)


def run_predict(config: Config, params: Dict[str, str]) -> None:
    """Predict path (application.cpp:252-260, predictor.hpp)."""
    if not config.data:
        Log.fatal("No data for prediction, application quit")
    if not config.input_model:
        Log.fatal("No model file for prediction, application quit")
    booster = Booster(params=dict(params), model_file=config.input_model)
    preds = booster.predict(
        config.data,
        num_iteration=config.num_iteration_predict,
        raw_score=config.is_predict_raw_score,
        pred_leaf=config.is_predict_leaf_index,
    )
    preds = np.atleast_1d(preds)
    with open(config.output_result, "w") as f:
        if preds.ndim == 1:
            for v in preds:
                f.write(f"{v:g}\n")
        else:
            for row in preds:
                f.write("\t".join(f"{v:g}" for v in row) + "\n")
    Log.info("Finished prediction, results saved to %s", config.output_result)


def main(argv: List[str] = None) -> int:
    """Application::Run (application.h:82, main.cpp:4-21).

    Two non-reference extensions: ``python -m lightgbm_tpu report
    <trace.jsonl>`` renders a TIMETAG-style summary of a structured run
    trace (docs/OBSERVABILITY.md), and ``python -m lightgbm_tpu serve
    model=... [key=value ...]`` runs the microbatching HTTP predict
    server over a packed artifact or model file (docs/SERVING.md)."""
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "report":
        from .obs.report import main as report_main

        return report_main(argv[1:])
    if argv and argv[0] == "serve":
        from .serve.server import main as serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "fleet":
        from .serve.fleet import main as fleet_main

        return fleet_main(argv[1:])
    if argv and argv[0] == "factory":
        from .factory.supervisor import main as factory_main

        return factory_main(argv[1:])
    if argv and argv[0] == "ingest":
        # subcommand sugar for task=ingest (matches report/serve style)
        argv = ["task=ingest"] + argv[1:]
    if argv and argv[0] == "resume":
        # subcommand sugar: task=train that REQUIRES a checkpoint to
        # resume from (docs/CHECKPOINT.md); plain task=train already
        # auto-resumes an interrupted run
        argv = ["task=train", "checkpoint_resume=force"] + argv[1:]
    from .parallel.net import CollectiveTimeoutError, PeerFailureError

    try:
        params = load_all_params(argv)
        config = Config.from_params(params)
        if config.task == "train":
            run_train(config, params)
        elif config.task in ("predict", "prediction", "test"):
            run_predict(config, params)
        elif config.task == "convert_model":
            run_convert_model(config, params)
        elif config.task == "ingest":
            run_ingest(config, params)
        else:
            Log.fatal("Unknown task type %s", config.task)
    except PeerFailureError as ex:
        Log.warning(
            "Peer failure after %.1fs (ranks %s): %s — restart the job to "
            "auto-resume from the last checkpoint",
            ex.elapsed_s, list(ex.ranks), ex,
        )
        return _net_exit(EXIT_PEER_FAILURE)
    except CollectiveTimeoutError as ex:
        Log.warning(
            "Collective/bootstrap timeout after %.1fs: %s — restart the "
            "job to auto-resume from the last checkpoint",
            ex.elapsed_s, ex,
        )
        return _net_exit(EXIT_NET_TIMEOUT)
    except Exception as ex:  # main.cpp catches and exits non-zero
        try:  # fatal path: leave a flight-recorder dump alongside the trace
            from .obs import flight

            flight.dump("fatal_error", error=ex)
        except Exception:
            pass
        Log.warning("Met Exceptions: %s", ex)
        return 1
    return 0


def _net_exit(code: int) -> int:
    """Leave after a transport failure.  In a multi-process runtime the
    survivors must NOT run interpreter atexit hooks: the JAX distributed
    shutdown barrier blocks ~100 s against the dead peer and then kills
    the process with a fatal log — so exit through ``net.hard_exit``.
    Single-process (bootstrap timeouts) returns normally."""
    try:
        from jax._src import distributed as _dist

        from .parallel.net import hard_exit

        if _dist.global_state.client is not None:
            import jax

            if jax.process_count() > 1:
                hard_exit(code)  # never returns
    except Exception:  # pragma: no cover - private-API drift tolerated
        pass
    return code


if __name__ == "__main__":
    sys.exit(main())
