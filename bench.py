"""Benchmark harness — the BASELINE.md north-star metric: sec/iteration
on Higgs-shaped data (docs/GPU-Performance.md:101-117 config: max_bin=63,
num_leaves=255, learning_rate=0.1, min_data_in_leaf=1,
min_sum_hessian_in_leaf=100).

The real Higgs download is unavailable (zero egress), so a synthetic
Higgs-shaped dataset is generated.  The informative weight vector is
drawn ONCE from a fixed seed and shared by every split, so train and
held-out rows describe the same task and the AUC is a real quality
signal (cross-checked against sklearn HistGradientBoosting at matched
hyperparameters; see auc_sklearn).

Rows default to 1M (vs Higgs 10.5M) to keep the harness fast;
per-iteration time scales linearly in N, so `vs_baseline` scales the
reference number to the measured row count.  Set BENCH_ROWS=10500000 for
the full-Higgs-scale run.

Prints ONE JSON line: {"metric": ..., "value": ..., "unit": ...,
"vs_baseline": ...}.
"""

import glob
import json
import os
import sys
import time

import numpy as np

_TASK_SEED = 20260730  # the task (informative weights) — NEVER varies
_N_INFORM = 8


# ----------------------------------------------------------------------
# perf regression gate: compare this run's s/iter against the best prior
# driver-captured BENCH_r*.json with the SAME metric line
# ----------------------------------------------------------------------
def best_prior_sec_per_iter(bench_dir: str, metric: str):
    """(best s/iter, source file) over prior BENCH_r*.json captures whose
    parsed metric matches ``metric`` exactly (same rows/config) and that
    ran on the real backend (backend_fallback runs are not comparable).
    (None, None) when no prior parses — first capture of a new config."""
    best, best_src = None, None
    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        if not isinstance(parsed, dict):
            # tolerate raw bench-format files ({"metric": ..., "value": ...})
            parsed = doc if isinstance(doc, dict) and "metric" in doc else None
        if not parsed or parsed.get("metric") != metric:
            continue
        if parsed.get("backend_fallback"):
            continue
        v = parsed.get("value")
        if isinstance(v, (int, float)) and v > 0 and (best is None or v < best):
            best, best_src = float(v), os.path.basename(path)
    return best, best_src


def apply_regression_gate(out: dict, bench_dir: str = None, env=None) -> int:
    """Annotate ``out`` with the gate verdict; return the process exit
    code (1 when this run is >10% slower than the best comparable prior
    capture).  BENCH_GATE=0 opts out; no matching prior => silent skip.
    A backend_fallback run never gates (CPU numbers are a different
    regime than the device numbers they would be compared to)."""
    env = env if env is not None else os.environ
    if env.get("BENCH_GATE", "1") == "0":
        return 0
    if bench_dir is None:
        bench_dir = os.path.dirname(os.path.abspath(__file__)) or "."
    rc = 0
    # comms payload-ratio leg FIRST (docs/PARALLEL.md): the bytes/iter
    # numbers are pure protocol arithmetic — deterministic and
    # device-INDEPENDENT — so voting's >=5x allreduce-payload cut over
    # data-parallel gates outright, even on a backend_fallback capture
    cm = out.get("comms") or {}
    ratio_c = cm.get("voting_vs_data_payload_ratio")
    if cm and not cm.get("error") and isinstance(ratio_c, (int, float)):
        out["gate_comms"] = {
            "min_voting_vs_data_payload_ratio": 5.0,
            "voting_vs_data_payload_ratio": round(float(ratio_c), 2),
        }
        if float(ratio_c) < 5.0:
            out["regression_comms_payload"] = True
            rc = 1
    # quantized-hist payload leg, same regime: the f32-vs-int16 histogram
    # wire ratio is protocol arithmetic (F*B*12 vs F*B*4), so the >=3x
    # contract gates even on backend_fallback captures
    qh = cm.get("quantized_hist") or {}
    ratio_q = qh.get("f32_vs_quantized_payload_ratio")
    if cm and not cm.get("error") and isinstance(ratio_q, (int, float)):
        out["gate_quantized_hist"] = {
            "min_f32_vs_quantized_payload_ratio": 3.0,
            "f32_vs_quantized_payload_ratio": round(float(ratio_q), 2),
        }
        if float(ratio_q) < 3.0:
            out["regression_quantized_hist_payload"] = True
            rc = 1
    # elastic recovery leg, same regime: the injected per-collective
    # stall dominates compute on any backend, so rebalance-on must beat
    # rebalance-off by >=1.3x under the ~4x straggler on EVERY capture —
    # CPU fallback included (docs/ROBUSTNESS.md)
    el = out.get("elastic") or {}
    rr = el.get("recovery_ratio")
    if el and not el.get("error") and isinstance(rr, (int, float)):
        out["gate_elastic"] = {
            "min_recovery_ratio": 1.3,
            "recovery_ratio": round(float(rr), 2),
        }
        if float(rr) < 1.3:
            out["regression_elastic_recovery"] = True
            rc = 1
    # distributed out-of-core quantized-parity leg, same regime: int32
    # per-chunk fold partials are associative, so the model bytes must
    # match EXACTLY across chunk grids — protocol arithmetic, gated
    # outright even on device_tunnel_dead captures (docs/DATA.md)
    od = out.get("ooc_distributed") or {}
    if od and not od.get("error") and "quantized_parity_ok" in od:
        out["gate_oocdist"] = {
            "require_quantized_parity": True,
            "quantized_parity_ok": bool(od["quantized_parity_ok"]),
            "chunk_grids": od.get("chunk_grids"),
        }
        if not od["quantized_parity_ok"]:
            out["regression_oocdist_parity"] = True
            rc = 1
    # linear-tree leg, same regime: trees-to-matched-logloss is a
    # quality-per-tree property of the fit math, not of the backend, so
    # the >=20% fewer-trees contract (ratio <= 0.8) gates outright
    # (docs/TREES.md)
    lt = out.get("linear_tree") or {}
    ratio_l = lt.get("trees_to_match_ratio")
    if lt and not lt.get("error") and isinstance(ratio_l, (int, float)):
        out["gate_linear_tree"] = {
            "max_trees_to_match_ratio": 0.8,
            "trees_to_match_ratio": round(float(ratio_l), 3),
        }
        if float(ratio_l) > 0.8:
            out["regression_linear_tree"] = True
            rc = 1
    # spot-economics leg, same regime: cost is member-seconds x price
    # arithmetic and the zero-lost-iterations record is write-once KV
    # bookkeeping — both device-independent, so the <=0.8x spot-vs-
    # static cost contract AND the nothing-redone proof gate outright
    # even on backend_fallback captures (docs/FACTORY.md)
    sp = out.get("spot") or {}
    if sp and not sp.get("error"):
        ratio_s = sp.get("cost_ratio_spot_vs_static")
        out["gate_spot"] = {
            "max_cost_ratio_spot_vs_static": 0.8,
            "cost_ratio_spot_vs_static": ratio_s,
            "require_zero_lost_iterations": True,
            "zero_lost_iterations": sp.get("zero_lost_iterations"),
        }
        if not sp.get("zero_lost_iterations"):
            out["regression_spot_lost_iterations"] = True
            rc = 1
        if isinstance(ratio_s, (int, float)) and float(ratio_s) > 0.8:
            out["regression_spot_cost"] = True
            rc = 1
    # serving-tail leg, same regime: the injected per-request delay
    # dominates any backend's own latency, so hedged p99 under chaos
    # staying <= 3x the healthy-baseline p99 is a protocol-level
    # contract of the hedging/breaker machinery — it gates outright
    # even on backend_fallback captures (docs/ROBUSTNESS.md)
    stl = out.get("serving_tail") or {}
    ratio_t = stl.get("hedged_chaos_over_healthy_p99")
    if stl and not stl.get("error") and isinstance(ratio_t, (int, float)):
        out["gate_serving_tail"] = {
            "max_hedged_chaos_over_healthy_p99": 3.0,
            "hedged_chaos_over_healthy_p99": round(float(ratio_t), 3),
        }
        if float(ratio_t) > 3.0:
            out["regression_serving_tail"] = True
            rc = 1
    if out.get("backend_fallback"):
        return rc
    best, src = best_prior_sec_per_iter(bench_dir, out.get("metric"))
    if best is not None:
        threshold = best * 1.10
        out["gate"] = {
            "best_prior_s_per_iter": round(best, 4),
            "best_prior_source": src,
            "threshold_s_per_iter": round(threshold, 4),
        }
        if float(out.get("value", 0.0)) > threshold:
            out["regression"] = True
            rc = 1
    # out-of-core leg: the streamed s/iter gates against prior captures
    # with the same (rows, chunk_rows) streaming grid
    sec = out.get("out_of_core") or {}
    val = sec.get("stream_s_per_iter")
    if isinstance(val, (int, float)) and val > 0 and not sec.get("error"):
        key = (sec.get("rows"), sec.get("chunk_rows"))
        best_o, src_o = None, None
        for path in sorted(glob.glob(os.path.join(bench_dir,
                                                  "BENCH_r*.json"))):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            parsed = doc.get("parsed") if isinstance(doc, dict) else None
            if not isinstance(parsed, dict):
                parsed = doc if isinstance(doc, dict) else {}
            if parsed.get("backend_fallback"):
                continue
            po = parsed.get("out_of_core") or {}
            pv = po.get("stream_s_per_iter")
            if (po.get("rows"), po.get("chunk_rows")) != key:
                continue
            if isinstance(pv, (int, float)) and pv > 0 and (
                    best_o is None or pv < best_o):
                best_o, src_o = float(pv), os.path.basename(path)
        if best_o is not None:
            thr_o = best_o * 1.10
            out["gate_ooc"] = {
                "best_prior_stream_s_per_iter": round(best_o, 4),
                "best_prior_source": src_o,
                "threshold_s_per_iter": round(thr_o, 4),
            }
            if float(val) > thr_o:
                out["regression_ooc"] = True
                rc = 1
    # serving-swap leg (independent): a hot swap to a same-shape retrain
    # must compile NOTHING (the tree-shape-bucket contract) — any
    # swap_new_compiles is a regression outright, no prior needed.  Swap
    # latency p99 gates against priors with the same swap count, at a
    # wider 1.5x threshold: the op is short host work (load + cache-hit
    # warmup), so its relative run-to-run variance dwarfs the s/iter legs'
    sw = (out.get("serving") or {}).get("swap") or {}
    if not sw.get("error"):
        if isinstance(sw.get("swap_new_compiles"), int) and \
                sw["swap_new_compiles"] > 0:
            out["regression_swap_compiles"] = True
            rc = 1
        val_s = sw.get("swap_latency_p99_ms")
        if isinstance(val_s, (int, float)) and val_s > 0:
            best_s, src_s = None, None
            for path in sorted(glob.glob(os.path.join(bench_dir,
                                                      "BENCH_r*.json"))):
                try:
                    with open(path) as f:
                        doc = json.load(f)
                except (OSError, ValueError):
                    continue
                parsed = doc.get("parsed") if isinstance(doc, dict) else None
                if not isinstance(parsed, dict):
                    parsed = doc if isinstance(doc, dict) else {}
                if parsed.get("backend_fallback"):
                    continue
                ps = (parsed.get("serving") or {}).get("swap") or {}
                pv = ps.get("swap_latency_p99_ms")
                if ps.get("swaps") != sw.get("swaps"):
                    continue
                if isinstance(pv, (int, float)) and pv > 0 and (
                        best_s is None or pv < best_s):
                    best_s, src_s = float(pv), os.path.basename(path)
            if best_s is not None:
                thr_s = best_s * 1.5
                out["gate_swap"] = {
                    "best_prior_swap_p99_ms": round(best_s, 3),
                    "best_prior_source": src_s,
                    "threshold_ms": round(thr_s, 3),
                }
                if float(val_s) > thr_s:
                    out["regression_swap"] = True
                    rc = 1
    # quantized leg (independent): three device-independent contracts
    # gate outright, no prior needed — the quantized same-shape swap must
    # compile NOTHING, the measured drift must sit inside its documented
    # bound, and the quantized payload must be at least 2x smaller.  The
    # batch-2048 speedup gates against the best prior capture's speedup
    # (not an absolute floor, so a faster exact baseline can't fail it
    # spuriously) at the same 1.10 slack as the s/iter legs.
    qz = out.get("quantized") or {}
    if qz and not qz.get("error"):
        qsw = qz.get("swap") or {}
        if isinstance(qsw.get("swap_new_compiles"), int) and \
                qsw["swap_new_compiles"] > 0:
            out["regression_quant_swap_compiles"] = True
            rc = 1
        dr = qz.get("drift") or {}
        if dr and not dr.get("within_bound"):
            out["regression_quant_drift"] = True
            rc = 1
        ab = qz.get("artifact_bytes") or {}
        ratio = ab.get("payload_ratio")
        if isinstance(ratio, (int, float)) and ratio < 2.0:
            out["regression_quant_bytes"] = True
            rc = 1
        val_q = (qz.get("batch2048") or {}).get("speedup")
        if isinstance(val_q, (int, float)) and val_q > 0:
            best_q, src_q = None, None
            for path in sorted(glob.glob(os.path.join(bench_dir,
                                                      "BENCH_r*.json"))):
                try:
                    with open(path) as f:
                        doc = json.load(f)
                except (OSError, ValueError):
                    continue
                parsed = doc.get("parsed") if isinstance(doc, dict) else None
                if not isinstance(parsed, dict):
                    parsed = doc if isinstance(doc, dict) else {}
                if parsed.get("backend_fallback"):
                    continue
                pq = ((parsed.get("quantized") or {}).get("batch2048")
                      or {}).get("speedup")
                if isinstance(pq, (int, float)) and pq > 0 and (
                        best_q is None or pq > best_q):
                    best_q, src_q = float(pq), os.path.basename(path)
            if best_q is not None:
                thr_q = best_q / 1.10
                out["gate_quantized"] = {
                    "best_prior_speedup_batch2048": round(best_q, 3),
                    "best_prior_source": src_q,
                    "threshold_speedup": round(thr_q, 3),
                }
                if float(val_q) < thr_q:
                    out["regression_quantized"] = True
                    rc = 1
    # multi-model leg (independent): the admission-refusal probe is a
    # device-independent correctness contract — a budget overrun that is
    # NOT refused loudly is a regression outright
    mm = out.get("multimodel") or {}
    if mm and not mm.get("error") and \
            mm.get("admission_refusal_ok") is False:
        out["regression_multimodel_admission"] = True
        rc = 1
    # factory leg (independent): the append->promoted e2e latency gates
    # against priors at the same (rows, num_boost_round) grid.  Wider
    # 1.5x threshold: the cycle is host work (staging, eval, registry
    # I/O) whose run-to-run variance dwarfs the s/iter legs'
    fa = out.get("factory") or {}
    val_f = fa.get("append_to_promoted_s")
    if isinstance(val_f, (int, float)) and val_f > 0 and not fa.get("error"):
        key_f = (fa.get("rows"), fa.get("num_boost_round"))
        best_f, src_f = None, None
        for path in sorted(glob.glob(os.path.join(bench_dir,
                                                  "BENCH_r*.json"))):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            parsed = doc.get("parsed") if isinstance(doc, dict) else None
            if not isinstance(parsed, dict):
                parsed = doc if isinstance(doc, dict) else {}
            if parsed.get("backend_fallback"):
                continue
            pf = parsed.get("factory") or {}
            if (pf.get("rows"), pf.get("num_boost_round")) != key_f:
                continue
            pv = pf.get("append_to_promoted_s")
            if isinstance(pv, (int, float)) and pv > 0 and (
                    best_f is None or pv < best_f):
                best_f, src_f = float(pv), os.path.basename(path)
        if best_f is not None:
            thr_f = best_f * 1.5
            out["gate_factory"] = {
                "best_prior_append_to_promoted_s": round(best_f, 3),
                "best_prior_source": src_f,
                "threshold_s": round(thr_f, 3),
            }
            if float(val_f) > thr_f:
                out["regression_factory"] = True
                rc = 1
    # comms wall-clock legs (device-bound, so non-fallback captures
    # only — the payload-ratio leg above already ran regardless): each
    # learner's s/iter gates against priors at the same
    # (rows, features, ranks) grid
    if cm and not cm.get("error"):
        key_c = (cm.get("rows"), cm.get("features"), cm.get("ranks"))
        for mode_c in ("data", "feature", "voting"):
            val_c = ((cm.get("per_learner") or {}).get(mode_c)
                     or {}).get("s_per_iter")
            if not (isinstance(val_c, (int, float)) and val_c > 0):
                continue
            best_c, src_c = None, None
            for path in sorted(glob.glob(os.path.join(bench_dir,
                                                      "BENCH_r*.json"))):
                try:
                    with open(path) as f:
                        doc = json.load(f)
                except (OSError, ValueError):
                    continue
                parsed = doc.get("parsed") if isinstance(doc, dict) else None
                if not isinstance(parsed, dict):
                    parsed = doc if isinstance(doc, dict) else {}
                if parsed.get("backend_fallback"):
                    continue
                pc = parsed.get("comms") or {}
                if (pc.get("rows"), pc.get("features"),
                        pc.get("ranks")) != key_c:
                    continue
                pv = ((pc.get("per_learner") or {}).get(mode_c)
                      or {}).get("s_per_iter")
                if isinstance(pv, (int, float)) and pv > 0 and (
                        best_c is None or pv < best_c):
                    best_c, src_c = float(pv), os.path.basename(path)
            if best_c is not None:
                thr_c = best_c * 1.10
                out.setdefault("gate_comms_wall", {})[mode_c] = {
                    "best_prior_s_per_iter": round(best_c, 4),
                    "best_prior_source": src_c,
                    "threshold_s_per_iter": round(thr_c, 4),
                }
                if float(val_c) > thr_c:
                    out["regression_comms_wall"] = True
                    rc = 1
    return rc


def _task_weights(n_features: int):
    rng = np.random.RandomState(_TASK_SEED)
    return rng.randn(_N_INFORM), n_features


def make_higgs_shaped(n_rows: int, n_features: int = 28, seed: int = 7):
    """Synthetic binary data with Higgs-like geometry: a few informative
    features plus noise features, mildly non-linear decision surface.
    ``seed`` draws the ROWS only; the task itself is fixed."""
    w, _ = _task_weights(n_features)
    rng = np.random.RandomState(seed)
    X = rng.randn(n_rows, n_features).astype(np.float32)
    margin = X[:, :_N_INFORM] @ w + 0.5 * X[:, 0] * X[:, 1] - 0.3 * X[:, 2] ** 2
    prob = 1.0 / (1.0 + np.exp(-margin / margin.std()))
    y = (rng.rand(n_rows) < prob).astype(np.float32)
    return X, y


def _report_partial_trace(trace_path, mode):
    """A dead/failed bench run still explains itself: summarize whatever
    per-iteration / per-phase records the child flushed before dying."""
    import sys

    if not os.path.exists(trace_path):
        return
    try:
        from lightgbm_tpu.obs import report

        summary = report.summarize(report.load_trace(trace_path))
        print(f"# levelgrow={mode} partial trace ({trace_path}):",
              file=sys.stderr)
        print("# " + json.dumps(summary), file=sys.stderr)
    except Exception as e:  # pragma: no cover - best-effort forensics
        print(f"# trace summary failed: {type(e).__name__}: {e}",
              file=sys.stderr)


def _bench_serving(booster, X, batch_sizes=(1, 128, 2048), reps=20):
    """Warm p50/p99 latency + throughput of the serving predictor at
    fixed batch sizes, with compile accounting (serve subsystem)."""
    from lightgbm_tpu.obs import compilewatch
    from lightgbm_tpu.serve.artifact import PackedPredictor, PredictorArtifact

    section = {}
    try:
        packed = PackedPredictor(PredictorArtifact.from_booster(booster))
        max_bucket = max(batch_sizes)
        c0 = compilewatch.total_compiles()
        warm = packed.warmup(max_bucket)
        section["warmup_s"] = warm["secs"]
        section["warmup_compiles"] = warm["compiles"]
        section["buckets"] = warm["buckets"]
        c1 = compilewatch.total_compiles()
        for bs in batch_sizes:
            bs = min(bs, X.shape[0])
            rows = np.ascontiguousarray(X[:bs], np.float64)
            lat = []
            for _ in range(reps):
                t0 = time.time()
                packed.predict(rows)
                lat.append(time.time() - t0)
            lat.sort()
            p50 = lat[len(lat) // 2]
            p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
            section[f"batch{bs}"] = {
                "p50_ms": round(1e3 * p50, 3),
                "p99_ms": round(1e3 * p99, 3),
                "rows_per_s": round(bs / p50, 1),
            }
        section["measure_new_compiles"] = compilewatch.total_compiles() - c1
        section["swap"] = _bench_swap(packed, max_bucket)
    except Exception as e:  # pragma: no cover — serving must not kill bench
        section["error"] = f"{type(e).__name__}: {e}"
    return section


def _bench_swap(packed, warmup_rows, n_swaps=5):
    """Hot-swap cost (serve/fleet.py): swap a warmed SwappablePredictor
    to a sequence of same-shape "retrains" (leaf values perturbed, tree
    shapes unchanged) and report swap latency p50/p99 plus the XLA
    compiles the swaps cost.  The tree-shape compile-cache buckets make
    the contract swap_new_compiles == 0 — the regression gate fails the
    run on any violation (apply_regression_gate, serving-swap leg)."""
    from lightgbm_tpu.ops.predict import TreeArrays
    from lightgbm_tpu.serve.artifact import PredictorArtifact
    from lightgbm_tpu.serve.fleet import SwappablePredictor

    section = {}
    try:
        art = packed.artifact
        swapper = SwappablePredictor(packed, version=1)
        lat_ms, new_compiles = [], 0
        for i in range(n_swaps):
            fields = {f: np.asarray(getattr(art.arrays, f))
                      for f in TreeArrays.FIELDS}
            fields["leaf_value"] = fields["leaf_value"] * (1.0 + 1e-9 * (i + 1))
            retrain = PredictorArtifact(TreeArrays(**fields), art.meta)
            stats = swapper.swap_to(retrain, version=i + 2,
                                    warmup_max_rows=warmup_rows)
            lat_ms.append(stats["swap_ms"])
            new_compiles += stats["new_compiles"]
        lat_ms.sort()
        section = {
            "swaps": n_swaps,
            "swap_latency_p50_ms": round(lat_ms[len(lat_ms) // 2], 3),
            "swap_latency_p99_ms": round(
                lat_ms[min(len(lat_ms) - 1, int(0.99 * len(lat_ms)))], 3),
            "swap_new_compiles": int(new_compiles),
        }
    except Exception as e:  # pragma: no cover — swap must not kill bench
        section["error"] = f"{type(e).__name__}: {e}"
    return section


def _bench_linear(X, y, base_params):
    """linear_tree section (docs/TREES.md): trees-to-matched-quality A/B
    against constant leaves, plus v3 linear-artifact serving rows/s.

    Both boosters train the same rows/rounds; the A/B counts how many
    linear trees reach the CONSTANT model's final validation logloss
    (``Booster.predict(num_iteration=i)`` makes the scan free — no
    retrains).  ``trees_to_match_ratio`` is the acceptance number: the
    issue's contract is linear reaching constant quality with >=20%
    fewer trees, so the regression gate fails any capture above 0.8 —
    outright, the ratio is a quality-per-tree property of the math, not
    of the backend.  BENCH_LINEAR=0 skips; BENCH_LINEAR_ROWS /
    BENCH_LINEAR_ITERS resize."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serve.artifact import PackedPredictor, PredictorArtifact

    section = {}
    try:
        rows = min(int(os.environ.get("BENCH_LINEAR_ROWS", 60_000)), len(X))
        iters = int(os.environ.get("BENCH_LINEAR_ITERS", 60))
        n_tr = int(rows * 0.8)
        Xt, yt = X[:n_tr], y[:n_tr]
        Xv, yv = X[n_tr:rows], y[n_tr:rows]
        params = {k: v for k, v in base_params.items()
                  if k not in ("tree_learner", "num_machines")}
        params.update(objective="binary", verbose=-1)
        section["rows"] = rows
        section["iters"] = iters

        def logloss(margin):
            p = 1.0 / (1.0 + np.exp(-np.asarray(margin, np.float64)))
            p = np.clip(p, 1e-15, 1 - 1e-15)
            return float(-np.mean(yv * np.log(p)
                                  + (1 - yv) * np.log(1 - p)))

        t0 = time.time()
        const = lgb.train(dict(params), lgb.Dataset(Xt, label=yt),
                          num_boost_round=iters, verbose_eval=False)
        section["const_train_s"] = round(time.time() - t0, 2)
        target = logloss(const.predict(Xv, raw_score=True))
        section["const_valid_logloss"] = round(target, 6)

        t0 = time.time()
        lin = lgb.train(dict(params, linear_tree=True, linear_lambda=0.01),
                        lgb.Dataset(Xt, label=yt),
                        num_boost_round=iters, verbose_eval=False)
        section["linear_train_s"] = round(time.time() - t0, 2)
        section["linear_valid_logloss"] = round(
            logloss(lin.predict(Xv, raw_score=True)), 6)

        matched = None
        for i in range(1, iters + 1):
            if logloss(lin.predict(Xv, raw_score=True,
                                   num_iteration=i)) <= target:
                matched = i
                break
        section["trees_to_match"] = matched
        section["trees_to_match_ratio"] = round(
            (matched if matched is not None else iters) / iters, 3)

        # v3 bucketed serving throughput (the artifact the A/B winner
        # actually ships): warm batch-2048 rows/s + compile accounting
        from lightgbm_tpu.obs import compilewatch

        packed = PackedPredictor(PredictorArtifact.from_booster(lin))
        bs = min(2048, rows)
        batch = np.ascontiguousarray(Xt[:bs], np.float64)
        packed.predict(batch)  # warm the bucket
        c0 = compilewatch.total_compiles()
        lat = []
        for _ in range(10):
            t0 = time.time()
            packed.predict(batch)
            lat.append(time.time() - t0)
        lat.sort()
        section["serve_batch_rows"] = bs
        section["serve_rows_per_s"] = round(bs / lat[len(lat) // 2], 1)
        section["serve_new_compiles"] = compilewatch.total_compiles() - c0
    except Exception as e:  # pragma: no cover — A/B must not kill bench
        section["error"] = f"{type(e).__name__}: {e}"
    return section


def _bench_quantized(booster, X, batch_sizes=(1, 128, 2048), reps=20):
    """Quantized-serving A/B (docs/SERVING.md): exact vs int16
    rank-quantized predictor at fixed batch sizes, the artifact size of
    both flavors, the measured leaf-narrowing drift against its
    documented bound, and the quantized same-shape hot-swap compile
    count (must be 0, same contract as the exact swap leg)."""
    import io

    from lightgbm_tpu.ops.predict import TreeArrays
    from lightgbm_tpu.ops.qpredict import drift_bound
    from lightgbm_tpu.serve.artifact import PackedPredictor, PredictorArtifact
    from lightgbm_tpu.serve.fleet import SwappablePredictor

    section = {}
    try:
        exact_art = PredictorArtifact.from_booster(booster)
        quant_art = exact_art.quantize()

        def _file_bytes(a):
            buf = io.BytesIO()
            a.save_to_bytes(buf)
            return len(buf.getvalue())

        def _payload_bytes(a):
            return int(sum(arr.nbytes for arr in a._payload().values()))

        exact = PackedPredictor(exact_art, quantized=False)
        quant = PackedPredictor(quant_art)
        section["artifact_bytes"] = {
            "exact_file": _file_bytes(exact_art),
            "quantized_file": _file_bytes(quant_art),
            "exact_payload": _payload_bytes(exact_art),
            "quantized_payload": _payload_bytes(quant_art),
            "exact_device": exact.device_bytes,
            "quantized_device": quant.device_bytes,
            "payload_ratio": round(_payload_bytes(exact_art)
                                   / max(_payload_bytes(quant_art), 1), 2),
            "device_ratio": round(exact.device_bytes
                                  / max(quant.device_bytes, 1), 2),
        }
        max_bucket = max(batch_sizes)
        exact.warmup(max_bucket)
        quant.warmup(max_bucket)
        sample = np.ascontiguousarray(X[:min(2048, X.shape[0])], np.float64)
        diff = float(np.abs(quant.predict(sample, raw_score=True)
                            - exact.predict(sample, raw_score=True)).max())
        bound = drift_bound(exact_art.arrays.leaf_value)
        section["drift"] = {"max_abs": diff, "bound": bound,
                            "within_bound": bool(diff <= bound)}
        for bs in batch_sizes:
            bs = min(bs, X.shape[0])
            rows = np.ascontiguousarray(X[:bs], np.float64)
            sub = {}
            for name, p in (("exact", exact), ("quantized", quant)):
                lat = []
                for _ in range(reps):
                    t0 = time.time()
                    p.predict(rows)
                    lat.append(time.time() - t0)
                lat.sort()
                p50 = lat[len(lat) // 2]
                sub[name] = {
                    "p50_ms": round(1e3 * p50, 3),
                    "p99_ms": round(
                        1e3 * lat[min(len(lat) - 1, int(0.99 * len(lat)))],
                        3),
                    "rows_per_s": round(bs / p50, 1),
                }
            sub["speedup"] = round(sub["quantized"]["rows_per_s"]
                                   / max(sub["exact"]["rows_per_s"], 1e-9), 3)
            section[f"batch{bs}"] = sub
        # quantized same-shape hot swap: zero new XLA compiles
        swapper = SwappablePredictor(quant, version=1)
        lat_ms, new_compiles = [], 0
        for i in range(3):
            fields = {f: np.asarray(getattr(exact_art.arrays, f))
                      for f in TreeArrays.FIELDS}
            fields["leaf_value"] = fields["leaf_value"] * (1.0 + 1e-4 * (i + 1))
            retrain = PredictorArtifact(
                TreeArrays(**fields), exact_art.meta).quantize()
            stats = swapper.swap_to(retrain, version=i + 2,
                                    warmup_max_rows=max_bucket)
            lat_ms.append(stats["swap_ms"])
            new_compiles += stats["new_compiles"]
        lat_ms.sort()
        section["swap"] = {
            "swaps": 3,
            "swap_latency_p50_ms": round(lat_ms[len(lat_ms) // 2], 3),
            "swap_new_compiles": int(new_compiles),
        }
    except Exception as e:  # pragma: no cover — must not kill bench
        section["error"] = f"{type(e).__name__}: {e}"
    return section


def _bench_multimodel(booster, X, n_models=4, reps=10, batch=128):
    """Multi-model bin-packing (docs/SERVING.md): N models behind named
    routes on ONE server process, per-model rows/s through the full
    HTTP + microbatch path, the shared device-bytes admission ledger,
    and a budget-refusal probe (the loud-failure contract)."""
    import json as _json
    import shutil
    import tempfile
    import threading
    import urllib.request

    from lightgbm_tpu.ops.predict import TreeArrays
    from lightgbm_tpu.serve.artifact import PredictorArtifact
    from lightgbm_tpu.serve.registry import ModelRegistry
    from lightgbm_tpu.serve.server import make_server

    section = {}
    tmp = tempfile.mkdtemp(prefix="ltpu-bench-mm-")
    srv = None
    try:
        art = PredictorArtifact.from_booster(booster)
        reg = ModelRegistry(os.path.join(tmp, "reg"))
        reg.publish(art)  # v1 = the default route
        routes = []
        for i in range(n_models - 1):
            fields = {f: np.asarray(getattr(art.arrays, f))
                      for f in TreeArrays.FIELDS}
            fields["leaf_value"] = fields["leaf_value"] * (1.0 + 0.1 * (i + 1))
            retrain = PredictorArtifact(TreeArrays(**fields), art.meta)
            if i % 2 == 0:  # alternate flavors to prove they co-pack
                retrain = retrain.quantize()
            v = reg.publish(retrain, activate=False)
            name = f"m{i + 1}"
            reg.set_route(name, v)
            routes.append(name)
        srv = make_server(registry_dir=reg.dir, port=0,
                          warmup_max_rows=batch, max_delay_ms=1.0,
                          registry_poll_ms=10_000.0)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        port = srv.server_address[1]
        rows = np.ascontiguousarray(X[:batch], np.float64)
        body = "\n".join(
            _json.dumps([float(v) for v in r]) for r in rows).encode()

        def _rows_per_s(path):
            lat = []
            for _ in range(reps):
                t0 = time.time()
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", data=body,
                    timeout=60).read()
                lat.append(time.time() - t0)
            lat.sort()
            return round(len(rows) / lat[len(lat) // 2], 1)

        per_model = {"default": _rows_per_s("/predict")}
        for name in routes:
            per_model[name] = _rows_per_s(f"/predict/{name}")
        section = {
            "n_models": n_models,
            "per_model_rows_per_s": per_model,
            "device_bytes_used": srv.device_bytes_used(),
        }
        # admission-refusal probe: a budget below the current usage must
        # refuse the next route loudly and leave the admitted ones alone
        srv.route_budget_bytes = srv.device_bytes_used() + 1
        reg.set_route("overbudget", 1)
        srv.sync_routes()
        refused = "overbudget" in srv.admission_refused
        still_serving = all(r in srv.routes for r in routes)
        section["admission_refusal_ok"] = bool(refused and still_serving)
    except Exception as e:  # pragma: no cover — must not kill bench
        section["error"] = f"{type(e).__name__}: {e}"
    finally:
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        shutil.rmtree(tmp, ignore_errors=True)
    return section


def _bench_ingest(X, y, n_rows):
    """Streaming-ingest benchmark (docs/DATA.md): write the bench matrix
    as CSV, stream it through the two-pass out-of-core pipeline, and
    report rows/s, chunk count and the peak-RSS bound that proves the
    raw float matrix was never materialized (the acceptance contract:
    peak RSS - start RSS < packed matrix + O(chunk), asserted via the
    obs memory gauges that data/ingest.py records).  BENCH_INGEST=0
    skips, BENCH_INGEST_ROWS caps the row count."""
    import tempfile

    from lightgbm_tpu.basic import Dataset

    section = {}
    rows = min(int(os.environ.get("BENCH_INGEST_ROWS", n_rows)), len(X))
    path = os.path.join(
        os.environ.get("BENCH_INGEST_DIR", tempfile.gettempdir()),
        f"bench_ingest_{rows}.csv",
    )
    try:
        t0 = time.time()
        import pandas as pd

        pd.DataFrame(np.column_stack([y[:rows], X[:rows]])).to_csv(
            path, index=False, header=False, float_format="%.7g"
        )
        section["write_csv_s"] = round(time.time() - t0, 2)
        section["csv_mb"] = round(os.path.getsize(path) / 1e6, 1)

        env_before = os.environ.get("LIGHTGBM_TPU_STREAM_INGEST")
        os.environ["LIGHTGBM_TPU_STREAM_INGEST"] = "1"
        try:
            t0 = time.time()
            ds = Dataset(path).construct()
            ingest_s = time.time() - t0
        finally:
            if env_before is None:
                os.environ.pop("LIGHTGBM_TPU_STREAM_INGEST", None)
            else:
                os.environ["LIGHTGBM_TPU_STREAM_INGEST"] = env_before
        rep = dict(getattr(ds, "ingest_report", {}))
        section.update({
            "rows": rows,
            "ingest_s": round(ingest_s, 2),
            "rows_per_s": round(rows / max(ingest_s, 1e-9), 1),
            "chunks": rep.get("chunks_pass2"),
            "chunk_rows": rep.get("chunk_rows"),
            "packed_mb": rep.get("packed_mb"),
            "rss_start_mb": rep.get("rss_start_mb"),
            "rss_peak_mb": rep.get("rss_peak_mb"),
            "sketch": rep.get("sketch"),
        })
        # the bound: packed matrix + a few in-flight chunk buffers
        # (parser scratch included) + fixed slack.  The raw float64
        # matrix would be rows*cols*8 bytes — reported alongside so the
        # separation is visible at a glance.
        chunk_raw_mb = (rep.get("chunk_rows", 0) * (X.shape[1] + 1) * 8) / 1e6
        bound_mb = (rep.get("packed_mb", 0.0) or 0.0) + 8 * chunk_raw_mb + 128
        increase = (rep.get("rss_peak_mb", 0.0) or 0.0) - (
            rep.get("rss_start_mb", 0.0) or 0.0
        )
        section["raw_matrix_mb"] = round(rows * (X.shape[1] + 1) * 8 / 1e6, 1)
        section["rss_increase_mb"] = round(increase, 1)
        section["rss_bound_mb"] = round(bound_mb, 1)
        section["rss_bound_ok"] = bool(increase <= bound_mb)
    except Exception as e:  # pragma: no cover — ingest must not kill bench
        section["error"] = f"{type(e).__name__}: {e}"
    finally:
        if os.environ.get("BENCH_INGEST_KEEP", "0") != "1":
            try:
                os.unlink(path)
            except OSError:
                pass
    return section


def _bench_checkpoint(X, y, base_params):
    """Checkpoint subsystem benchmark (docs/CHECKPOINT.md): save latency
    p50/p99, checkpoint bytes, and the per-iteration overhead of
    background-write checkpointing at freq in {0, 10, 1} on the standard
    bench config (acceptance: freq=10 overhead < 5%).  BENCH_CKPT=0
    skips; BENCH_CKPT_ROWS / BENCH_CKPT_ITERS resize."""
    import shutil
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu.ckpt import CheckpointManager

    section = {}
    rows = min(int(os.environ.get("BENCH_CKPT_ROWS", 200_000)), len(X))
    iters = int(os.environ.get("BENCH_CKPT_ITERS", 30))
    Xb, yb = X[:rows], y[:rows]
    try:
        # warmup run compiles the train programs so the freq=0 baseline
        # isn't charged for compilation the other configs then reuse
        lgb.train(dict(base_params), lgb.Dataset(Xb, label=yb,
                  params=dict(base_params)), 3, verbose_eval=False)
        times = {}
        stats10 = None
        for freq in (0, 10, 1):
            d = tempfile.mkdtemp(prefix="bench_ckpt_")
            mgr = CheckpointManager(d, freq=freq) if freq > 0 else None
            ds = lgb.Dataset(Xb, label=yb, params=dict(base_params))
            t0 = time.time()
            lgb.train(dict(base_params), ds, iters, verbose_eval=False,
                      checkpoint_manager=mgr)
            times[freq] = time.time() - t0
            if mgr is not None:
                mgr.close()
                if freq == 10:
                    stats10 = dict(mgr.stats)
            shutil.rmtree(d, ignore_errors=True)
        base = max(times[0], 1e-9)
        section = {
            "rows": rows,
            "iters": iters,
            "total_s": {f"freq{k}": round(v, 3) for k, v in times.items()},
            "overhead_freq10_pct": round(100.0 * (times[10] - base) / base, 2),
            "overhead_freq1_pct": round(100.0 * (times[1] - base) / base, 2),
        }
        if stats10:
            lat = sorted(stats10["save_s"])
            if lat:
                section["save_p50_ms"] = round(1e3 * lat[len(lat) // 2], 2)
                section["save_p99_ms"] = round(
                    1e3 * lat[min(len(lat) - 1, int(0.99 * len(lat)))], 2
                )
            section["ckpt_bytes"] = stats10["bytes"]
            section["saves_freq10"] = stats10["saves"]
    except Exception as e:  # pragma: no cover — ckpt must not kill bench
        section["error"] = f"{type(e).__name__}: {e}"
    return section


def _bench_ooc(X, y, base_params):
    """Out-of-core streaming benchmark (docs/DATA.md "Out-of-core
    training"): streamed vs resident s/iter over the same rows, prefetch
    overlap (how much of the host->device fetch hid behind compute), and
    the bounded-residency check (peak in-flight chunks <= ring depth, the
    O(2 chunks) contract).  BENCH_OOC=0 skips; BENCH_OOC_ROWS /
    BENCH_OOC_ITERS / BENCH_OOC_CHUNK_ROWS resize.  Model parity at this
    scale is informational only — the byte-identity contract is pinned at
    masked-scan scale by tests/test_ooc.py."""
    import lightgbm_tpu as lgb

    section = {}
    rows = min(int(os.environ.get("BENCH_OOC_ROWS", 200_000)), len(X))
    iters = int(os.environ.get("BENCH_OOC_ITERS", 10))
    chunk_rows = int(os.environ.get("BENCH_OOC_CHUNK_ROWS", 65_536))
    Xb, yb = X[:rows], y[:rows]
    P_mem = dict(base_params, out_of_core="false")
    P_ooc = dict(base_params, out_of_core="true", ooc_chunk_rows=chunk_rows)
    try:
        # warmup compiles both program sets so neither timed leg pays it
        for P in (P_mem, P_ooc):
            lgb.train(dict(P), lgb.Dataset(Xb, label=yb, params=dict(P)),
                      2, verbose_eval=False)
        t0 = time.time()
        b_mem = lgb.train(dict(P_mem),
                          lgb.Dataset(Xb, label=yb, params=dict(P_mem)),
                          iters, verbose_eval=False)
        mem_s = time.time() - t0
        t0 = time.time()
        b_ooc = lgb.train(dict(P_ooc),
                          lgb.Dataset(Xb, label=yb, params=dict(P_ooc)),
                          iters, verbose_eval=False)
        ooc_s = time.time() - t0
        ooc = b_ooc.boosting.ooc
        st = ooc.stats.as_dict()
        section = {
            "rows": rows,
            "iters": iters,
            "chunk_rows": ooc.plan.chunk_rows,
            "chunks": ooc.plan.num_chunks,
            "prefetch_depth": ooc.depth,
            "resident_s_per_iter": round(mem_s / iters, 4),
            "stream_s_per_iter": round(ooc_s / iters, 4),
            "stream_vs_resident": round(ooc_s / max(mem_s, 1e-9), 3),
            "stream_rows_per_s": round(rows * iters / max(ooc_s, 1e-9)),
            "streamed_mb": round(st["bytes"] / 1e6, 1),
            "overlap_pct": st["overlap_pct"],
            "fetch_s": st["fetch_s"],
            "stall_s": st["stall_s"],
            "peak_inflight": st["peak_inflight"],
            "residency_ok": bool(st["peak_inflight"] <= ooc.depth),
            "models_match": bool(
                b_mem.model_to_string() == b_ooc.model_to_string()),
        }
    except Exception as e:  # pragma: no cover — ooc must not kill bench
        section["error"] = f"{type(e).__name__}: {e}"
    return section


def _bench_factory(X, y):
    """Continuous-training factory benchmark (docs/FACTORY.md): the
    append->promoted end-to-end latency of one warm-started cycle
    (canary off — the watcher/retrain/publish/promote path itself), the
    warm-start cost against a tree-count-matched cold retrain over the
    same data, and the canary-window plumbing overhead (replica spawn +
    bounded observation window + teardown, measured against an idle
    proxy with min_requests=0).  BENCH_FACTORY=0 skips;
    BENCH_FACTORY_ROWS resizes."""
    import shutil
    import tempfile
    import threading

    from lightgbm_tpu.factory import FactorySupervisor
    from lightgbm_tpu.serve.fleet import FleetProxy, _free_ports

    section = {}
    rows = min(int(os.environ.get("BENCH_FACTORY_ROWS", 8_000)), len(X))
    rounds = 10
    knobs = {"num_boost_round": rounds, "checkpoint_freq": 5,
             "debounce_ms": 0.0, "canary_fraction": 0.0}
    params = {"objective": "binary", "num_leaves": 31, "verbose": -1,
              "min_data_in_leaf": 5}
    root = tempfile.mkdtemp(prefix="bench_factory_")

    def write_chunk(data_dir, name, lo, hi):
        path = os.path.join(data_dir, name)
        with open(path, "a") as f:
            np.savetxt(f, np.column_stack([y[lo:hi], X[lo:hi]]),
                       fmt="%.6g", delimiter=",")
        t = time.time() - 60  # out of the debounce window
        os.utime(path, (t, t))

    try:
        data_dir = os.path.join(root, "data")
        os.makedirs(data_dir)
        write_chunk(data_dir, "chunk-000.csv", 0, rows // 2)
        sup = FactorySupervisor(data_dir, os.path.join(root, "work"),
                                os.path.join(root, "reg"),
                                params=dict(params), **knobs)
        t0 = time.time()
        v1 = sup.run_cycle()
        bootstrap_s = time.time() - t0
        # the headline number: a chunk append -> warm retrain -> publish
        # -> eval gate -> activate, end to end
        write_chunk(data_dir, "chunk-001.csv", rows // 2, rows)
        t0 = time.time()
        v2 = sup.run_cycle()
        warm_s = time.time() - t0
        # cold control at the same final tree count (v1's rounds + the
        # warm delta) over the same data — what skipping the warm start
        # would have cost
        cold = FactorySupervisor(data_dir, os.path.join(root, "work2"),
                                 os.path.join(root, "reg2"),
                                 params=dict(params),
                                 **dict(knobs, num_boost_round=2 * rounds))
        t0 = time.time()
        vc = cold.run_cycle()
        cold_s = time.time() - t0
        section = {
            "rows": rows,
            "num_boost_round": rounds,
            "bootstrap_cycle_s": round(bootstrap_s, 3),
            "append_to_promoted_s": round(warm_s, 3),
            "warm_start": bool(v2["warm_start"]),
            "cold_equivalent_s": round(cold_s, 3),
            "warm_vs_cold_speedup": round(cold_s / max(warm_s, 1e-9), 3),
            "verdicts_ok": bool(
                v1["verdict"] == v2["verdict"] == vc["verdict"]
                == "promoted"),
        }
        # canary-window overhead: the same cycle shape with the canary
        # plumbing live (pin-version replica spawn + observe window +
        # teardown) against an idle proxy; min_requests=0 keeps the
        # verdict a promote so the two latencies are comparable
        if os.environ.get("BENCH_FACTORY_CANARY", "1") != "0":
            # the proxy only serves its local /fleet/canary endpoint
            # here; its one "backend" is a dead address no /predict ever
            # routes through
            proxy = FleetProxy(("127.0.0.1", 0),
                               [f"127.0.0.1:{_free_ports(1)[0]}"],
                               health_poll_s=0.5, retry_deadline_s=5.0)
            threading.Thread(target=proxy.serve_forever,
                             daemon=True).start()
            try:
                write_chunk(data_dir, "chunk-002.csv", 0, rows // 4)
                csup = FactorySupervisor(
                    data_dir, os.path.join(root, "work"),
                    os.path.join(root, "reg"), params=dict(params),
                    proxy=f"127.0.0.1:{proxy.server_address[1]}",
                    **dict(knobs, canary_fraction=0.25, observe_s=1.0,
                           min_requests=0))
                t0 = time.time()
                v3 = csup.run_cycle()
                canary_s = time.time() - t0
                section["canary_cycle_s"] = round(canary_s, 3)
                section["canary_overhead_s"] = round(canary_s - warm_s, 3)
                section["canary_verdict"] = v3["verdict"]
            finally:
                proxy.shutdown()
                proxy.server_close()
    except Exception as e:  # pragma: no cover — factory must not kill bench
        section["error"] = f"{type(e).__name__}: {e}"
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return section


def _bench_serving_tail(booster, X):
    """Serving-tail benchmark (docs/ROBUSTNESS.md): hedged vs unhedged
    client p99 through a 3-replica subprocess fleet whose first replica
    is wounded with an injected per-request delay via
    ``LIGHTGBM_TPU_SERVE_FAULT`` — the gray-failure scenario the hedging
    + breaker machinery exists for.  Three proxy legs over the same
    fleet: healthy (clean replicas only), chaos unhedged, chaos hedged.
    The hedged-chaos-over-healthy p99 ratio is protocol-level (the
    injected delay dominates any backend's own latency), so it is the
    device-independent leg of the regression gate.  BENCH_SERVING_TAIL=0
    skips; BENCH_SERVING_TAIL_REQS resizes the per-leg request count."""
    import shutil
    import tempfile
    import threading
    import urllib.request

    from lightgbm_tpu.serve import ModelRegistry, PredictorArtifact
    from lightgbm_tpu.serve.fleet import (FleetProxy, _wait_ready,
                                          spawn_replicas)

    section = {}
    reps = int(os.environ.get("BENCH_SERVING_TAIL_REQS", 90))
    delay_ms = 300.0
    hedge_ms = 25.0
    root = tempfile.mkdtemp(prefix="bench_servetail_")
    procs = []

    def p99(lats):
        vals = sorted(lats)
        return vals[min(len(vals) - 1, int(0.99 * len(vals)))]

    def measure(backends, hedge_delay_ms):
        proxy = FleetProxy(("127.0.0.1", 0), backends,
                           health_poll_s=0.2, retry_deadline_s=20.0,
                           backend_timeout_s=5.0,
                           hedge_delay_ms=hedge_delay_ms,
                           hedge_budget_pct=100.0)
        threading.Thread(target=proxy.serve_forever, daemon=True).start()
        lats = []
        try:
            url = f"http://127.0.0.1:{proxy.server_address[1]}/predict"
            for _ in range(reps):
                req = urllib.request.Request(url, data=body)
                req.add_header("X-Deadline-Ms", "15000")
                t0 = time.perf_counter()
                urllib.request.urlopen(req, timeout=60).read()
                lats.append(time.perf_counter() - t0)
            return lats, proxy.stats()
        finally:
            proxy.shutdown()
            proxy.server_close()

    try:
        reg_dir = os.path.join(root, "reg")
        ModelRegistry(reg_dir).publish(
            PredictorArtifact.from_booster(booster))
        # replicas are pinned to CPU: the tail numbers are protocol-
        # level (delay-dominated), and the bench's own device stays free
        cpu = {"JAX_PLATFORMS": "cpu"}
        procs = spawn_replicas(
            3, {"registry": reg_dir, "warmup_max_rows": "64",
                "max_delay_ms": "1", "registry_poll_ms": "200"},
            envs=[dict(cpu, LIGHTGBM_TPU_SERVE_FAULT=f"delay:{delay_ms:g}"),
                  dict(cpu), dict(cpu)])
        for _, port in procs:
            if not _wait_ready("127.0.0.1", port, 180.0):
                raise RuntimeError(f"replica on port {port} never ready")
        addrs = [f"127.0.0.1:{p}" for _, p in procs]
        body = "\n".join(json.dumps(list(map(float, r)))
                         for r in np.asarray(X[:2], float)).encode()

        healthy_lats, _ = measure(addrs[1:], -1.0)
        unhedged_lats, _ = measure(addrs, -1.0)
        hedged_lats, hst = measure(addrs, hedge_ms)

        # the ratio denominator is floored: a microsecond-fast healthy
        # fleet would otherwise turn the fixed hedge delay into a huge
        # "slowdown" that says nothing about tail behavior
        floor_s = 0.020
        healthy_p99 = p99(healthy_lats)
        denom = max(healthy_p99, floor_s)
        section = {
            "requests_per_leg": reps,
            "injected_delay_ms": delay_ms,
            "hedge_delay_ms": hedge_ms,
            "gate_floor_ms": round(1e3 * floor_s, 1),
            "healthy_p99_ms": round(1e3 * healthy_p99, 2),
            "unhedged_chaos_p99_ms": round(1e3 * p99(unhedged_lats), 2),
            "hedged_chaos_p99_ms": round(1e3 * p99(hedged_lats), 2),
            "unhedged_chaos_over_healthy_p99": round(
                p99(unhedged_lats) / denom, 3),
            "hedged_chaos_over_healthy_p99": round(
                p99(hedged_lats) / denom, 3),
            "hedges_launched": hst["hedges"]["launched"],
            "hedge_wins": hst["hedges"]["wins"],
        }
    except Exception as e:  # pragma: no cover — tail bench must not kill bench
        section["error"] = f"{type(e).__name__}: {e}"
    finally:
        for p, _ in procs:
            p.kill()
        for p, _ in procs:
            try:
                p.wait(timeout=30)
            except Exception:
                pass
        shutil.rmtree(root, ignore_errors=True)
    return section


def _bench_kernel_ab():
    """Kernel-level A/B microbenches (PR-6 speed push), runnable in CPU
    interpret mode when the device tunnel is dead: (1) one multi-leaf
    hist_segments launch vs per-leaf hist_dyn launches, (2) the score-only
    band settle vs the old full update+hist settle, (3) GOSS's
    histogram-free gradient-prep pass vs the old discarded-histogram
    pass, (4) the tuned one-hot fchunk vs the legacy 512//B rule (cost
    model — fchunk is bit-invariant so only the MXU row count changes).
    Every A/B also reports the max abs diff of the results it compares
    so the wins are demonstrated WITH parity, not instead of it."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops import histogram_pallas as hp
    from lightgbm_tpu.ops import pkernels as pk

    interp = jax.default_backend() != "tpu"
    section = {"interpret_mode": interp}
    reps = int(os.environ.get("BENCH_KERNEL_AB_REPS", 3))

    def timed(fn):
        fn()  # warm (compile)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    try:
        rng = np.random.RandomState(3)
        n, f, b, L = 32768, 16, 32, 8
        lay = pk.PLayout(f)
        bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
        P = pk.pack_matrix(bins, lay, label=(rng.rand(n) < 0.5).astype(np.float32))
        g = rng.randn(n).astype(np.float32)
        h = np.abs(rng.randn(n)).astype(np.float32)
        P = P.at[lay.G, :n].set(jnp.asarray(g.view(np.int32)))
        P = P.at[lay.H, :n].set(jnp.asarray(h.view(np.int32)))

        # ---- (1) multi-leaf level histograms: L launches -> 1 launch
        edges = np.linspace(0, n, L + 1).astype(np.int32)
        segs = np.stack([edges[:-1], edges[1:] - edges[:-1]], 1).astype(np.int32)
        segs_j = jnp.asarray(segs)

        def per_leaf():
            outs = [
                pk.hist_dyn(P, int(s), int(c), f, b, rows=lay.rows,
                            interpret=interp)
                for s, c in segs
            ]
            jax.block_until_ready(outs)
            return outs

        def multi():
            out = hp.hist_segments(P, segs_j, L, num_features=f, num_bins=b,
                                   rows=lay.rows, smax=L, interpret=interp)
            jax.block_until_ready(out)
            return out

        t_per, t_multi = timed(per_leaf), timed(multi)
        diff = float(np.abs(
            np.stack([np.asarray(x) for x in per_leaf()]) - np.asarray(multi())
        ).max())
        section["multi_leaf_hist"] = {
            "launches_per_level_before": L,
            "launches_per_level_after": 1,
            "per_leaf_s": round(t_per, 4),
            "one_launch_s": round(t_multi, 4),
            "speedup": round(t_per / max(t_multi, 1e-9), 2),
            "max_abs_diff": diff,
            # the win this buys on the tunneled device is the per-launch
            # fixed cost (~0.3 ms measured in r3) x (leaves-1) per level;
            # interpret mode can only demonstrate compute parity
            "note": "device win = per-launch fixed cost x (L-1)/level",
        }

        # ---- (2) chunk-end settle: full update+hist pass -> band settle
        delta = rng.randn(n).astype(np.float32)

        def grad_fn(score, label, weight):
            ps = 1.0 / (1.0 + jnp.exp(-score))
            return (ps - label) * weight, ps * (1.0 - ps) * weight

        def settle_full():
            p2, _ = pk.update_and_root_hist(
                jnp.array(P), lay, grad_fn, delta=jnp.asarray(delta),
                num_rows=n, num_features=f, num_bins=b, interpret=interp)
            jax.block_until_ready(p2)
            return p2

        def settle_band():
            p2 = pk.score_add(jnp.array(P), lay, jnp.asarray(delta), 0,
                              num_rows=n, interpret=interp)
            jax.block_until_ready(p2)
            return p2

        t_full, t_band = timed(settle_full), timed(settle_band)
        s_full = np.asarray(settle_full())[lay.SCORE, :n]
        s_band = np.asarray(settle_band())[lay.SCORE, :n]
        section["score_settle"] = {
            "full_pass_s": round(t_full, 4),
            "band_settle_s": round(t_band, 4),
            "speedup": round(t_full / max(t_band, 1e-9), 2),
            "scores_bit_identical": bool(np.array_equal(s_full, s_band)),
        }

        # ---- (3) GOSS gradient prep: discarded-histogram pass -> hist-free
        def prep(with_hist):
            def run():
                p2, _ = pk.update_and_root_hist(
                    jnp.array(P), lay, grad_fn, delta=jnp.asarray(delta),
                    num_rows=n, num_features=f, num_bins=b,
                    with_hist=with_hist, interpret=interp)
                jax.block_until_ready(p2)
                return p2
            return run

        t_hist, t_free = timed(prep(True)), timed(prep(False))
        a, c = np.asarray(prep(True)()), np.asarray(prep(False)())
        section["goss_prep"] = {
            "with_hist_s": round(t_hist, 4),
            "hist_free_s": round(t_free, 4),
            "speedup": round(t_hist / max(t_free, 1e-9), 2),
            "matrix_bit_identical": bool(np.array_equal(a, c)),
        }

        # ---- (4) tuned one-hot fchunk (bit-invariant; cost model)
        bench_f, bench_b = 28, 63  # the 1Mx28 max_bin=63 bench shape
        legacy = max(1, min(bench_f, 512 // bench_b))
        tuned = hp.tune_fchunk(bench_f, bench_b)
        section["hist_fchunk"] = {
            "shape": f"F={bench_f} B={bench_b}",
            "legacy": legacy,
            "tuned": tuned,
            "est_mxu_rows_legacy": hp.fchunk_cost(bench_f, bench_b, legacy),
            "est_mxu_rows_tuned": hp.fchunk_cost(bench_f, bench_b, tuned),
        }

        # ---- (5) int32 vs f32 histogram accumulation (quantized
        # training, non-gating): same blocked one-hot contraction, int16
        # values with preferred_element_type=int32.  The A/B's real story
        # is the exactness column: the int path is row-order INVARIANT
        # (integer adds are associative) where the f32 path is not, and
        # the Pallas int kernel matches the XLA int path bit for bit —
        # the f32 kernel only matches to float tolerance.
        from lightgbm_tpu.ops import qhist
        from lightgbm_tpu.ops.histogram import build_histogram

        sel = jnp.ones((n,), jnp.float32)
        gj, hj = jnp.asarray(g), jnp.asarray(h)
        scales = qhist.scales_from_max(float(np.abs(g).max()),
                                       float(np.abs(h).max()),
                                       qhist.QUANT_BITS)
        qg, qh2 = qhist.quantize_rows(gj, hj, jnp.asarray(scales),
                                      np.uint32(1), qhist.QUANT_BITS)
        bj = jnp.asarray(bins)

        def acc_f32():
            out = build_histogram(bj, gj, hj, sel, b)
            jax.block_until_ready(out)
            return out

        def acc_int():
            out = build_histogram(bj, qg, qh2, sel, b)
            jax.block_until_ready(out)
            return out

        t_f32a, t_inta = timed(acc_f32), timed(acc_int)
        # row-order invariance: shuffle the rows, rebuild, compare bytes
        perm = rng.permutation(n)
        hist_i = np.asarray(acc_int())
        hist_ip = np.asarray(build_histogram(
            bj[perm], qg[perm], qh2[perm], sel, b))
        hist_f = np.asarray(acc_f32())
        hist_fp = np.asarray(build_histogram(
            bj[perm], gj[jnp.asarray(perm)], hj[jnp.asarray(perm)], sel, b))
        # Pallas interpret-mode parity of the int kernel vs the XLA path
        Pq = hp.pack_columns_q(bj, qg, qh2, sel)
        pall_q = np.asarray(hp.hist_segment_q(
            Pq, jnp.int32(0), jnp.int32(n), num_features=f, num_bins=b,
            interpret=True))
        section["quantized_hist_accum"] = {
            "f32_s": round(t_f32a, 4),
            "int32_s": round(t_inta, 4),
            "speedup": round(t_f32a / max(t_inta, 1e-9), 2),
            "int_row_order_invariant": bool(
                np.array_equal(hist_i, hist_ip)),
            "f32_row_order_invariant": bool(
                np.array_equal(hist_f, hist_fp)),
            "pallas_int_bit_identical_to_xla": bool(
                np.array_equal(pall_q, hist_i)),
            "dequant_max_abs_err": float(np.abs(
                np.asarray(qhist.dequantize_hist(
                    jnp.asarray(hist_i), jnp.asarray(scales))) - hist_f
            ).max()),
            "note": "non-gating; exactness columns are the contract",
        }
    except Exception as e:  # pragma: no cover — A/B must not kill bench
        section["error"] = f"{type(e).__name__}: {e}"
    return section


def _bench_comms():
    """Comms-volume A/B of the three distributed tree learners
    (docs/PARALLEL.md) on a synthetic WIDE matrix (>= 2000 features):
    purpose-tagged bytes/iter and s/iter per learner over an in-process
    2-rank LocalComm group (parallel/comm.py) — the same learner code
    the KV transport drives, minus the network, so the byte ledger is
    exact protocol arithmetic.  The voting-vs-data payload ratio is
    deterministic and device-independent (it gates even on
    backend_fallback captures); the s/iter numbers are device-bound.
    BENCH_COMMS=0 skips; BENCH_COMMS_FEATURES / BENCH_COMMS_ROWS /
    BENCH_COMMS_ITERS / BENCH_COMMS_TOPK resize."""
    import threading

    import jax.numpy as jnp

    from lightgbm_tpu.ops.grow import GrowParams
    from lightgbm_tpu.ops.split import FeatureMeta, SplitHyper
    from lightgbm_tpu.parallel import HostParallelLearner, LocalGroup

    F = int(os.environ.get("BENCH_COMMS_FEATURES", 2000))
    n = int(os.environ.get("BENCH_COMMS_ROWS", 3000))
    iters = int(os.environ.get("BENCH_COMMS_ITERS", 2))
    top_k = int(os.environ.get("BENCH_COMMS_TOPK", 20))
    B, R = 16, 2
    try:
        rng = np.random.RandomState(23)
        bins = rng.randint(0, B, size=(n, F)).astype(np.uint8)
        grad = (bins[:, :8].astype(np.float32)
                @ rng.randn(8).astype(np.float32) / B
                + 0.05 * rng.randn(n).astype(np.float32)
                ).astype(np.float32)
        hess = np.ones(n, np.float32)
        meta = FeatureMeta(jnp.full((F,), B, jnp.int32),
                           jnp.zeros((F,), jnp.int32),
                           jnp.zeros((F,), bool))
        hyper = SplitHyper(jnp.float32(0.0), jnp.float32(0.1),
                           jnp.float32(20.0), jnp.float32(1e-3),
                           jnp.float32(0.0))
        fmask = jnp.ones((F,), jnp.float32)
        # small row_block: the histogram one-hot tile is
        # row_block x (F*B) f32 — the default 4096 rows would be 1 GB
        # at F=2000
        params = GrowParams(num_leaves=15, num_bins=B, row_block=256,
                            top_k=top_k)
        params_q = params._replace(quantized=True)
        cut = n // 2

        def run(mode, quantized=False):
            sh = ([(bins, grad, hess)] * R if mode == "feature"
                  else [(bins[:cut], grad[:cut], hess[:cut]),
                        (bins[cut:], grad[cut:], hess[cut:])])
            grp = LocalGroup(R)
            ledgers = [None] * R
            errs = []

            def worker(r, comm, reps):
                try:
                    b, g, h = sh[r]
                    ln = HostParallelLearner(
                        mode, comm, params_q if quantized else params)
                    for _ in range(reps):
                        ln.grow(jnp.asarray(b), jnp.asarray(g),
                                jnp.asarray(h),
                                jnp.ones((b.shape[0],), jnp.float32),
                                fmask, meta, hyper)
                    ledgers[r] = dict(comm.ledger)
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            def sweep(reps):
                ts = [threading.Thread(target=worker, args=(r, c, reps))
                      for r, c in enumerate(grp.comms())]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                if errs:
                    raise errs[0]

            sweep(1)  # warmup: compile the mode's kernels off the clock
            warm = dict(ledgers[0])
            t0 = time.time()
            sweep(iters)
            wall = time.time() - t0
            total = sum(ledgers[0].values()) - sum(warm.values())
            return {
                "bytes_per_iter": round(total / max(iters, 1), 1),
                "s_per_iter": round(wall / max(iters, 1), 4),
                "ledger_bytes_per_iter": {
                    k: round((ledgers[0][k] - warm.get(k, 0))
                             / max(iters, 1), 1)
                    for k in sorted(ledgers[0])
                },
            }

        per = {m: run(m) for m in ("data", "feature", "voting")}
        d_b = per["data"]["bytes_per_iter"]
        v_b = per["voting"]["bytes_per_iter"]
        f_b = per["feature"]["bytes_per_iter"]
        out = {
            "rows": n, "features": F, "ranks": R, "iters": iters,
            "top_k": top_k,
            "per_learner": per,
            "voting_vs_data_payload_ratio":
                round(d_b / v_b, 2) if v_b else None,
            "feature_vs_data_payload_ratio":
                round(d_b / f_b, 2) if f_b else None,
        }
        # quantized-training histogram wire (docs/PARALLEL.md): the
        # f32-vs-int16 per-histogram payload is pure protocol arithmetic
        # — F*B*12 bytes (f32 g/h/cnt planes) vs F*B*4 (int16 g/h, count
        # derived at the receiver) — so the >=3x ratio is exact and
        # device-independent; a measured data-parallel run over the same
        # LocalComm group corroborates it from the byte ledger (slightly
        # under 3x: the scale maxima + int root sums ride "hist_q" too)
        from lightgbm_tpu.ops import qhist

        f32_hist = qhist.wire_bytes_f32(F, B)
        q_hist = qhist.wire_bytes_q(F, B)
        qdata = run("data", quantized=True)
        led_f = per["data"]["ledger_bytes_per_iter"].get("hist", 0.0)
        led_q = qdata["ledger_bytes_per_iter"].get("hist_q", 0.0)
        out["quantized_hist"] = {
            "f32_bytes_per_hist": f32_hist,
            "int16_bytes_per_hist": q_hist,
            "f32_vs_quantized_payload_ratio": round(f32_hist / q_hist, 2),
            "measured_data_quantized": qdata,
            "measured_hist_bytes_per_iter_f32": led_f,
            "measured_hist_bytes_per_iter_q": led_q,
            "measured_ratio": (round(led_f / led_q, 2) if led_q else None),
        }
        return out
    except Exception as e:  # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def _bench_elastic():
    """Elastic straggler A/B (docs/ROBUSTNESS.md): three REAL 2-rank
    subprocess fleets (tests/elastic_worker.py over the KV transport)
    training the same data-parallel job —

      no_straggler        — clean baseline
      straggler_off       — rank 0 sleeps ``delay:ms:after:N`` at every
                            hardened collective (a ~4x per-row-slow
                            host), rebalancing DISABLED
      straggler_rebalance — same fault, ``rebalance=true``: the
                            controller moves rows off the slow rank and
                            the injected stall shrinks with them
                            (net.set_delay_scale ties sleep to the
                            current/initial row ratio)

    reporting steady-state s/iter (tail iterations, past warmup and the
    move) and ``recovery_ratio = off / on``.  The injected stall
    dominates compute on ANY backend, so the >=1.3x recovery contract is
    device-independent and gates outright even on backend_fallback
    captures (apply_regression_gate).  BENCH_ELASTIC=0 skips;
    BENCH_ELASTIC_ROWS / BENCH_ELASTIC_TREES / BENCH_ELASTIC_DELAY_MS
    resize."""
    import socket
    import subprocess
    import sys as _sys
    import tempfile

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", "elastic_worker.py")
    rows = int(os.environ.get("BENCH_ELASTIC_ROWS", 1024))
    trees = int(os.environ.get("BENCH_ELASTIC_TREES", 14))
    delay_ms = int(os.environ.get("BENCH_ELASTIC_DELAY_MS", 30))
    tail = 5  # steady-state window: past warmup AND past the move
    try:
        if not os.path.exists(worker):
            return {"error": f"FileNotFoundError: {worker}"}

        def fleet(tag, extra_env, tmp):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            base = {k: v for k, v in os.environ.items()
                    if k not in ("XLA_FLAGS", "JAX_PLATFORMS",
                                 "LIGHTGBM_TPU_FAULT",
                                 "LIGHTGBM_TPU_FAULT_RANK",
                                 "LIGHTGBM_TPU_TRACE")}
            repo = os.path.dirname(os.path.abspath(__file__))
            base["PYTHONPATH"] = repo + os.pathsep + base.get(
                "PYTHONPATH", "")
            base.update(ELASTIC_ROWS=str(rows), ELASTIC_TREES=str(trees),
                        ELASTIC_FREQ="100")  # no checkpoint I/O on the clock
            base.update(extra_env)
            outp = os.path.join(tmp, tag)
            procs = [subprocess.Popen(
                [_sys.executable, worker, str(r), "2", str(port), outp,
                 "train", os.path.join(tmp, tag + "_ck")],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=base) for r in range(2)]
            logs = [p.communicate(timeout=600)[0] for p in procs]
            if any(p.returncode != 0 for p in procs):
                raise RuntimeError(
                    "elastic fleet failed: " + logs[0][-500:])
            res = []
            for r in range(2):
                with open(outp + f".rank{r}.json") as fh:
                    res.append(json.load(fh))
            return res

        fault = {"LIGHTGBM_TPU_FAULT": f"delay:{delay_ms}:after:5",
                 "LIGHTGBM_TPU_FAULT_RANK": "0"}

        def s_per_iter(res):
            # ranks run in lockstep (barrier-synchronized); the fleet
            # pace is either rank's tail-mean
            ts = res[0]["it_times"][-tail:]
            return sum(ts) / max(len(ts), 1)

        with tempfile.TemporaryDirectory(prefix="bench_elastic_") as tmp:
            base_r = fleet("base", {}, tmp)
            off_r = fleet("off", dict(fault), tmp)
            on_r = fleet("on", dict(fault, ELASTIC_REBALANCE="1",
                                    ELASTIC_MOVE_FRAC="0.6"), tmp)
        base_s = s_per_iter(base_r)
        off_s = s_per_iter(off_r)
        on_s = s_per_iter(on_r)
        return {
            "rows": rows, "trees": trees, "ranks": 2,
            "delay_ms_per_collective": delay_ms,
            "no_straggler_s_per_iter": round(base_s, 4),
            "straggler_off_s_per_iter": round(off_s, 4),
            "straggler_rebalance_s_per_iter": round(on_s, 4),
            "straggler_slowdown": (round(off_s / base_s, 2)
                                   if base_s > 0 else None),
            "recovery_ratio": (round(off_s / on_s, 2)
                               if on_s > 0 else None),
            "final_counts": on_r[0]["final_counts"],
        }
    except Exception as e:  # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def _bench_spot():
    """Spot-economics A/B (docs/FACTORY.md "spot"): one elastic
    2-member fleet (tests/membership_worker.py over the file-KV
    membership runtime, factory/spot.py driver) run through a scripted
    2-preemption capacity trace at the spot price, vs the same fleet
    left static at the on-demand price.  Reports cost-per-completed-
    model on both ledgers, their ratio, resize-pause p50/p99 from the
    survivors, and the zero-lost-iterations proof from the write-once
    per-iteration KV records.  Cost is member-seconds x price
    arithmetic — device-independent — so the <=0.8x ratio and the
    nothing-redone contract gate outright even on backend_fallback
    captures (apply_regression_gate).  BENCH_SPOT=0 skips;
    BENCH_SPOT_ROWS / BENCH_SPOT_TREES / BENCH_SPOT_PRICE resize."""
    import tempfile

    from lightgbm_tpu.factory.spot import (ON_DEMAND_PRICE, SpotFleet,
                                           SpotSchedule,
                                           run_static_baseline)

    rows = int(os.environ.get("BENCH_SPOT_ROWS", 600))
    trees = int(os.environ.get("BENCH_SPOT_TREES", 16))
    price = float(os.environ.get("BENCH_SPOT_PRICE", "0.3"))
    # pacing keeps the scripted event times inside the run on a fast
    # box; it inflates spot and static member-seconds identically, so
    # the cost ratio is pacing-invariant
    pace = {"MEMBER_ITER_SLEEP": os.environ.get("BENCH_SPOT_PACE", "0.8")}
    # preempt member 1 early (the fleet resizes to one survivor), spawn
    # replacement capacity right after (it auto-resumes from the
    # coordinator handoff), then preempt member 0 late — the replacement
    # finishes the model alone
    script = "preempt@5=1;spawn@6;preempt@20=0"
    try:
        with tempfile.TemporaryDirectory(prefix="bench_spot_") as tmp:
            static = run_static_baseline(
                os.path.join(tmp, "static"), 2,
                os.path.join(tmp, "static_ledger.json"),
                trees=trees, rows=rows, extra_env=dict(pace))
            if static["cost"] is None:
                raise RuntimeError(
                    f"static fleet incomplete: exits={static['exits']}")
            fleet = SpotFleet(
                os.path.join(tmp, "spot"),
                SpotSchedule.from_script(script, price), 2,
                os.path.join(tmp, "spot_ledger.json"),
                trees=trees, rows=rows, extra_env=dict(pace))
            spot = fleet.run()
            if spot["cost"] is None:
                raise RuntimeError(
                    f"spot fleet incomplete: exits={spot['exits']}")
            pauses = sorted(
                p for meta in spot["metas"].values()
                for p in meta.get("resize_pauses") or [])

        def pct(q):
            if not pauses:
                return None
            return round(pauses[min(len(pauses) - 1,
                                    int(q * len(pauses)))], 4)

        return {
            "rows": rows, "trees": trees, "members": 2,
            "schedule": script,
            "spot_price": price, "on_demand_price": ON_DEMAND_PRICE,
            "static_cost_per_model": round(static["cost"], 3),
            "spot_cost_per_model": round(spot["cost"], 3),
            "cost_ratio_spot_vs_static": round(
                spot["cost"] / static["cost"], 3),
            "preemptions": sum(1 for e in fleet.schedule.events
                               if e.kind == "preempt"),
            "resize_pauses": len(pauses),
            "resize_pause_p50_s": pct(0.50),
            "resize_pause_p99_s": pct(0.99),
            "zero_lost_iterations": bool(spot["zero_lost_iterations"]),
            "static_wall_s": static["wall_s"],
            "spot_wall_s": spot["wall_s"],
        }
    except Exception as e:  # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def _bench_ooc_distributed():
    """Distributed out-of-core section (docs/DATA.md "Distributed
    streaming", docs/PARALLEL.md): two REAL 2-rank subprocess fleets
    (tests/oocdist_worker.py — every rank streams its own shard through
    the prefetch ring, node histograms allreduced on the ``hist_q``
    wire) trained under quantized_training at two DIFFERENT per-rank
    chunk grids, then a byte-compare of the final models.

    ``quantized_parity_ok`` is the integer-fold associativity contract:
    per-chunk int32 partials cannot depend on the chunk grid, so the
    model bytes must match EXACTLY — protocol arithmetic, not a timing,
    which is why the gate holds it outright even on backend_fallback /
    device_tunnel_dead captures (apply_regression_gate).
    BENCH_OOCDIST=0 skips; BENCH_OOCDIST_ROWS / BENCH_OOCDIST_TREES
    resize."""
    import socket
    import subprocess
    import sys as _sys
    import tempfile

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", "oocdist_worker.py")
    rows = int(os.environ.get("BENCH_OOCDIST_ROWS", 16384))
    trees = int(os.environ.get("BENCH_OOCDIST_TREES", 3))
    grids = (2048, 9999)  # round to 4096 (2 chunks/rank) vs 12288 (1)
    try:
        if not os.path.exists(worker):
            return {"error": f"FileNotFoundError: {worker}"}

        def fleet(tag, grid, tmp):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            base = {k: v for k, v in os.environ.items()
                    if k not in ("XLA_FLAGS", "JAX_PLATFORMS",
                                 "LIGHTGBM_TPU_FAULT",
                                 "LIGHTGBM_TPU_FAULT_RANK",
                                 "LIGHTGBM_TPU_TRACE",
                                 "LIGHTGBM_TPU_OOC",
                                 "LIGHTGBM_TPU_DEVICE_BUDGET")}
            repo = os.path.dirname(os.path.abspath(__file__))
            base["PYTHONPATH"] = repo + os.pathsep + base.get(
                "PYTHONPATH", "")
            base.update(OOCDIST_ROWS=str(rows), OOCDIST_TREES=str(trees),
                        OOCDIST_OOC="true", OOCDIST_QUANT="1",
                        OOCDIST_LEAVES="15",
                        OOCDIST_CHUNK_ROWS=str(grid))
            outp = os.path.join(tmp, tag)
            t0 = time.time()
            procs = [subprocess.Popen(
                [_sys.executable, worker, str(r), "2", str(port), outp,
                 "train", "-"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=dict(base)) for r in range(2)]
            logs = [p.communicate(timeout=600)[0] for p in procs]
            if any(p.returncode != 0 for p in procs):
                raise RuntimeError(
                    "oocdist fleet failed: " + logs[0][-500:])
            wall = time.time() - t0
            models, stats = [], []
            for r in range(2):
                with open(outp + f".rank{r}.txt") as fh:
                    models.append(fh.read())
                with open(outp + f".rank{r}.json") as fh:
                    stats.append(json.load(fh))
            return models, stats, wall

        with tempfile.TemporaryDirectory(prefix="bench_oocdist_") as tmp:
            runs = {g: fleet(f"g{g}", g, tmp) for g in grids}
        ref = runs[grids[0]][0][0]
        parity = all(m == ref for models, _, _ in runs.values()
                     for m in models)
        g0 = runs[grids[0]][1][0]
        return {
            "rows": rows, "trees": trees, "ranks": 2,
            "chunk_grids": list(grids),
            "chunks_per_pass": {
                g: runs[g][1][0]["chunks_per_pass"] for g in grids},
            "fleet_wall_s": {
                g: round(runs[g][2], 2) for g in grids},
            "stream_stats_rank0": g0["stream_stats"],
            "quantized_parity_ok": parity,
        }
    except Exception as e:  # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def _auc(y, s):
    """AUC via the library's own metric (one implementation to trust)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.metric.binary import AUCMetric

    class _Meta:
        label = y
        weights = None

    m = AUCMetric(Config())
    m.init(_Meta, len(y))
    return m.eval(s)[0][1]


def main():
    # Safety wrapper: the level-batched grower is the fast default, but
    # its Mosaic compile is the newest moving part — if it hangs or the
    # remote compiler fails, the bench must still produce a number.  Run
    # the real bench as a subprocess with LIGHTGBM_TPU_LEVELGROW=1 and a
    # hard timeout; fall back to the per-split grower on any failure.
    if ("LIGHTGBM_TPU_LEVELGROW" not in os.environ
            and os.environ.get("BENCH_NO_GUARD", "0") != "1"):
        import subprocess

        # fail FAST when the accelerator is unreachable: a dead axon
        # tunnel makes backend init hang far past any useful timeout.
        # A dead/failed probe downgrades to JAX_PLATFORMS=cpu (flagged as
        # backend_fallback in the JSON) instead of killing the run: a CPU
        # number with a flag beats no number (round-5 died here, rc=1).
        probe_ok = False
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import os, jax;"
                 "p = os.environ.get('JAX_PLATFORMS', '');"
                 "p and jax.config.update('jax_platforms', p);"
                 "print(jax.default_backend())"],
                timeout=int(os.environ.get("BENCH_PROBE_TIMEOUT", 180)),
                capture_output=True, text=True,
            )
            backend = (probe.stdout or "").strip().splitlines()[-1:] or [""]
            backend = backend[0]
            probe_ok = probe.returncode == 0 and bool(backend)
            if not probe_ok:
                print("# device backend probe failed:\n"
                      + (probe.stderr or "")[-800:], file=sys.stderr)
            elif backend == "cpu" and os.environ.get("JAX_PLATFORMS", "") == "cpu":
                # the environment pins CPU (no accelerator reachable at
                # all): the device headline cannot be produced — run the
                # downscaled, flagged fallback config instead of grinding
                # the full 1M config through the host for an hour
                print("# backend probe returned cpu (JAX_PLATFORMS=cpu): "
                      "no accelerator — using the flagged fallback sizing",
                      file=sys.stderr)
                os.environ["BENCH_BACKEND_FALLBACK"] = "1"
        except subprocess.TimeoutExpired:
            print("# device backend init timed out (dead tunnel?)",
                  file=sys.stderr)
        if not probe_ok:
            if os.environ.get("JAX_PLATFORMS", "") == "cpu":
                # the fallback platform itself is broken — nothing to
                # try.  Still a self-flagged CAPTURE, not a process
                # failure: BENCH_r05 recorded rc:1 from this class and
                # capture automation filed it as a bench failure instead
                # of recording the dead-tunnel flag.  rc=1 stays reserved
                # for real regression-gate verdicts.
                print("# cpu backend probe failed — no benchmark possible",
                      file=sys.stderr)
                print(json.dumps({
                    "metric": "bench unavailable (backend init failed)",
                    "value": None,
                    "backend_fallback": True,
                    "device_tunnel_dead": True,
                    "error": "backend probe failed/timed out and the cpu "
                             "fallback probe also failed",
                }))
                sys.exit(0)
            # LOUD: this is the BENCH_r05 failure class — the PR-5
            # watchdog semantics (bounded probe, typed loud failure)
            # applied to the bench harness.  The run continues on CPU so
            # a number + kernel A/B still land, but nobody can mistake
            # this capture for a device measurement.
            print("#" * 64, file=sys.stderr)
            print("# DEVICE TUNNEL DEAD: backend probe failed/timed out.\n"
                  "# Falling back to JAX_PLATFORMS=cpu — this capture is\n"
                  "# flagged backend_fallback/device_tunnel_dead and will\n"
                  "# NOT be compared against device captures by the\n"
                  "# regression gate.", file=sys.stderr)
            print("#" * 64, file=sys.stderr)
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ["BENCH_BACKEND_FALLBACK"] = "1"

        # budget scales with the configured row count (Higgs-scale runs
        # legitimately take much longer than the 1M default)
        rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
        budget = int(os.environ.get(
            "BENCH_GUARD_TIMEOUT",
            2400 + max(0, rows - 1_000_000) // 2000,
        ))
        for mode in ("1", "0"):
            env = dict(os.environ, LIGHTGBM_TPU_LEVELGROW=mode)
            # run trace: always on for the child (obs/trace.py JSONL) so a
            # FAILED bench still leaves the per-phase records it gathered
            # before death; the path survives the subprocess boundary
            trace_path = env.get("LIGHTGBM_TPU_TRACE") or os.path.abspath(
                f"bench_trace.levelgrow{mode}.jsonl"
            )
            env["LIGHTGBM_TPU_TRACE"] = trace_path
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    env=env, timeout=budget, capture_output=True, text=True,
                )
            except subprocess.TimeoutExpired:
                print(f"# levelgrow={mode} bench timed out after {budget}s",
                      file=sys.stderr)
                _report_partial_trace(trace_path, mode)
                continue
            if '"metric"' in r.stdout:
                # a produced metric line is a successful MEASUREMENT even
                # when rc != 0 — that is the regression gate firing; the
                # verdict (and exit code) must propagate, not be retried
                line = [ln for ln in r.stdout.splitlines() if '"metric"' in ln][-1]
                if mode == "0":
                    out = json.loads(line)
                    out["grower_fallback"] = "per-split (levelwise failed)"
                    line = json.dumps(out)
                print(line)
                if r.returncode != 0:
                    print(f"# regression gate fired (rc={r.returncode})",
                          file=sys.stderr)
                sys.exit(r.returncode)
            print(f"# levelgrow={mode} bench failed rc={r.returncode}:\n"
                  + (r.stderr or "")[-2000:], file=sys.stderr)
            _report_partial_trace(trace_path, mode)
        if os.environ.get("BENCH_BACKEND_FALLBACK") == "1":
            # both children died on the cpu fallback of a dead tunnel:
            # emit a minimal self-flagged capture and exit 0 so the
            # driver records the device_tunnel_dead flag instead of a
            # failure (the BENCH_r05 rc:1 class); rc=1 stays reserved
            # for regression-gate verdicts
            print(json.dumps({
                "metric": "bench incomplete (device tunnel dead)",
                "value": None,
                "backend_fallback": True,
                "device_tunnel_dead": True,
                "error": "no child bench produced a metric line on the "
                         "cpu fallback",
            }))
            sys.exit(0)
        sys.exit(1)

    backend_fallback = os.environ.get("BENCH_BACKEND_FALLBACK") == "1"
    if backend_fallback and "BENCH_ROWS" not in os.environ:
        # dead tunnel: a 1M-row 255-leaf CPU run would blow the guard
        # budget for a number nobody compares against device captures
        # anyway — shrink rows AND leaves to what the CPU mask grower
        # finishes.  The changed metric string (rows + leaves are part of
        # it) guarantees the gate never cross-compares the regimes.
        # measured: the CPU mask grower runs ~0.5 s/split at 50k rows (the
        # one-hot matmul materializes ~360 MB per split), so the fallback
        # config must be MUCH smaller than the device one to fit the
        # guard budget with the eval A/B included
        n_rows = int(os.environ.get("BENCH_FALLBACK_ROWS", 10_000))
        n_leaves = int(os.environ.get("BENCH_FALLBACK_LEAVES", 31))
        n_iters_default = 12
    else:
        n_rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
        n_leaves = 255
        n_iters_default = 96
    # 96 iters / 3 windows: each window is ONE fused chunk dispatch of 32
    # iterations — the tunnel's per-dispatch fixed cost (~0.1-0.4 s per
    # chunk call) amortizes below ~3% instead of polluting short windows
    n_iters = int(os.environ.get("BENCH_ITERS", n_iters_default))
    warmup = int(os.environ.get("BENCH_WARMUP", 3))
    n_windows_default = 3
    crosscheck = os.environ.get("BENCH_SKIP_CROSSCHECK", "0") != "1"
    # eval-overhead A/B: measured by DEFAULT (it was built in r5 and then
    # never ran because it was opt-in); BENCH_VALID=0 skips
    with_valid = os.environ.get("BENCH_VALID", "1") == "1"

    import jax

    import lightgbm_tpu as lgb
    from lightgbm_tpu.basic import Booster, Dataset

    X, y = make_higgs_shaped(n_rows, seed=7)
    Xt, yt = make_higgs_shaped(200_000, seed=11)  # held-out rows, SAME task
    params = {
        "objective": "binary",
        "metric": "auc",
        "max_bin": 63,
        "num_leaves": n_leaves,
        "learning_rate": 0.1,
        "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100,
        "verbose": -1,
    }
    t0 = time.time()
    ds = Dataset(X, label=y, params=dict(params))
    booster = Booster(params=params, train_set=ds)
    gb = booster.boosting
    fused = gb.ptrainer is not None
    prep_s = time.time() - t0

    def run_iters(k):
        if fused:
            gb.train_iters_partitioned(k, is_eval=False)
        else:
            for _ in range(k):
                booster.update()
        # force completion: a host transfer (block_until_ready is a no-op
        # on the tunneled axon platform)
        np.asarray(gb.scores[0, :1])

    t0 = time.time()
    run_iters(warmup)
    warmup_s = time.time() - t0

    # timed windows, median: the tunneled device shows ~±20% run-to-run
    # drift, and per-tree cost grows slightly as boosting deepens trees —
    # the median window is the honest sustained rate; min is reported too
    # so A/B comparisons can see through one-off link stalls
    n_windows = int(os.environ.get("BENCH_NWINDOWS", n_windows_default))
    windows = []
    per = max(1, n_iters // n_windows)
    total_iters = warmup + n_windows * per
    for _ in range(n_windows):
        t0 = time.time()
        run_iters(per)
        windows.append((time.time() - t0) / per)
    sec_per_iter = float(np.median(windows))

    # ---- phase attribution pass (after the timed windows, so the
    # defused traced mode cannot pollute the s/iter number): a few extra
    # iterations with per-phase fencing explain where the time goes ----
    from lightgbm_tpu.obs import compilewatch, tracer

    attrib_iters = int(os.environ.get("BENCH_ATTRIB_ITERS", 2))
    if tracer.enabled and fused and attrib_iters > 0 and getattr(
        gb.ptrainer, "supports_traced", False
    ) and gb.num_tree_per_iteration == 1:
        phases_before = os.environ.get("LIGHTGBM_TPU_TRACE_PHASES")
        os.environ["LIGHTGBM_TPU_TRACE_PHASES"] = "1"
        tracer._phases_env = "1"
        try:
            gb.train_iters_partitioned(attrib_iters, is_eval=False)
            total_iters += attrib_iters
        finally:
            if phases_before is None:
                os.environ.pop("LIGHTGBM_TPU_TRACE_PHASES", None)
                tracer._phases_env = ""
            else:
                os.environ["LIGHTGBM_TPU_TRACE_PHASES"] = phases_before
                tracer._phases_env = phases_before

    # ---- xprof capture (LIGHTGBM_TPU_XPROF=dir): bounded device-
    # profiler window over a few already-warm iterations, after the
    # timed windows so the profiler overhead cannot touch s/iter ----
    from lightgbm_tpu.utils.profiling import maybe_xprof_capture

    xprof = maybe_xprof_capture()
    xprof_info = None
    if xprof is not None:
        xprof.skip = 0  # the timed windows above already warmed up
        for _ in range(xprof.iters):
            xprof.on_iter_start()
            run_iters(1)
            xprof.on_iter_end()
        xprof.close()
        total_iters += xprof.iters
        xprof_info = {"dir": xprof.log_dir, "iters": xprof.iters}

    # ---- quality signal on held-out rows of the SAME task ----
    prob = booster.predict(Xt)
    auc = _auc(yt, prob)

    auc_sk = None
    if crosscheck:
        try:
            from sklearn.ensemble import HistGradientBoostingClassifier

            sk = HistGradientBoostingClassifier(
                max_iter=total_iters,
                learning_rate=0.1,
                max_leaf_nodes=n_leaves,
                max_bins=63,
                min_samples_leaf=1,
                l2_regularization=0.0,
                early_stopping=False,
                validation_fraction=None,
            )
            sk_n = min(n_rows, 1_000_000)
            sk.fit(X[:sk_n], y[:sk_n])
            auc_sk = _auc(yt, sk.predict_proba(Xt)[:, 1])
        except Exception as e:  # pragma: no cover
            auc_sk = f"failed: {type(e).__name__}"

    # vs_baseline: the reference GPU (GTX 1080) trains Higgs-10.5M at about
    # 0.58 s/iter at this config (docs/GPU-Performance.md external chart,
    # commonly-cited ~290 s / 500 iters); scale to the measured row count.
    ref_gpu_sec_per_iter_higgs = 0.58
    ref_scaled = ref_gpu_sec_per_iter_higgs * (n_rows / 10_500_000)
    vs_baseline = ref_scaled / sec_per_iter if sec_per_iter > 0 else 0.0

    out = {
        "metric": f"sec/iteration (binary, {n_rows}x28, max_bin=63, num_leaves={n_leaves})",
        "value": round(sec_per_iter, 4),
        "unit": "s/iter",
        "vs_baseline": round(vs_baseline, 3),
        f"auc_heldout_{total_iters}iters": round(float(auc), 5),
        "auc_sklearn_same_iters": (round(float(auc_sk), 5) if isinstance(auc_sk, float) else auc_sk),
        "windows_s_per_iter": [round(w, 4) for w in windows],
        "window_min_s_per_iter": round(float(np.min(windows)), 4),
        "prep_s": round(prep_s, 2),
        "warmup_s": round(warmup_s, 2),
        "learner": "partitioned-fused" if fused else "mask-grower",
        "device": str(jax.devices()[0]).split(":")[0],
    }
    if backend_fallback:
        out["backend_fallback"] = True
        out["device_tunnel_dead"] = True
    if xprof_info is not None:
        out["xprof"] = xprof_info

    # same-box measured CPU baseline (refbuild/measure_baseline.py writes
    # it into BASELINE.json "published"); the GPU number above remains
    # chart hearsay, so the measured ratio is reported alongside
    try:
        with open(os.path.join(os.path.dirname(__file__) or ".", "BASELINE.json")) as f:
            pub = json.load(f).get("published", {})
        key = "ref_cpu_sec_per_iter_1m_rows"
        if key in pub:
            ref_cpu = float(pub[key]) * (n_rows / 1_000_000)
            # only the 1M-row config is genuinely measured; other row
            # counts are a linear extrapolation and labeled as such
            suffix = "" if n_rows == 1_000_000 else "_extrapolated_linear"
            out["ref_cpu_measured_s_per_iter" + suffix] = round(ref_cpu, 4)
            out["ref_cpu_threads"] = pub.get("ref_cpu_threads")
            out["vs_ref_cpu_same_box" + suffix] = round(ref_cpu / sec_per_iter, 3)
    except Exception:
        pass

    # eval-alive fused path (BENCH_VALID=1): train WITH a valid set +
    # device AUC at output_freq-period eval points; reports s/iter with
    # eval included so the eval overhead vs the eval-free number above is
    # directly visible (target: within ~15%)
    if with_valid:
        # end-to-end A/B at matched iteration count: a fresh eval-free
        # run vs a fresh run with a valid set + device AUC at output_freq
        # eval points.  Both include prep + compile, so the RATIO is the
        # honest eval overhead (timing only the iterations isn't possible
        # through lgb.train's single call).
        pv = dict(params)
        pv["output_freq"] = 16
        t0 = time.time()
        lgb.train(dict(params), lgb.Dataset(X, label=y, params=dict(params)),
                  num_boost_round=total_iters, verbose_eval=False)
        ref_total = time.time() - t0
        dtr = lgb.Dataset(X, label=y, params=dict(pv))
        # reference= shares the TRAIN bin mappers: tree thresholds are
        # train-mapper bin ids, so the valid set must be binned with them
        dv = lgb.Dataset(Xt, label=yt, reference=dtr)
        t0 = time.time()
        lgb.train(pv, dtr, num_boost_round=total_iters,
                  valid_sets=[dv], verbose_eval=False)
        eval_total = time.time() - t0
        out["valid_run_total_s"] = round(eval_total, 2)
        out["evalfree_run_total_s"] = round(ref_total, 2)
        out["valid_overhead_ratio"] = round(eval_total / max(ref_total, 1e-9), 3)
        out["eval_overhead_pct"] = round(
            100.0 * (eval_total / max(ref_total, 1e-9) - 1.0), 2
        )

    # serving section (docs/SERVING.md): warm inference latency through
    # the packed-artifact + bucketed-compile-cache path, so BENCH_r*
    # tracks inference regressions alongside training ones.  Warmup
    # compiles the bucket ladder; the measured loop must then show zero
    # new compiles (the serving acceptance contract).
    if os.environ.get("BENCH_SERVING", "0" if backend_fallback else "1") != "0":
        out["serving"] = _bench_serving(booster, X)

    # quantized-serving section (docs/SERVING.md): exact vs int16
    # rank-quantized predictor rows/s, both artifact flavors' bytes, the
    # measured leaf drift vs its bound, and the quantized same-shape
    # swap compile count — its own regression-gate leg
    if os.environ.get("BENCH_QUANT", "0" if backend_fallback else "1") != "0":
        out["quantized"] = _bench_quantized(booster, X)

    # linear-tree section (docs/TREES.md): trees-to-matched-logloss A/B
    # vs constant leaves + v3 serving rows/s.  Runs even on
    # backend_fallback: the fewer-trees ratio is quality-per-tree math,
    # the device-independent leg of the regression gate.
    if os.environ.get("BENCH_LINEAR", "1") != "0":
        out["linear_tree"] = _bench_linear(X, y, params)

    # multi-model section (docs/SERVING.md): N=4 models bin-packed on
    # one chip behind named routes, per-model rows/s through the full
    # HTTP path, and the admission-refusal probe
    if os.environ.get("BENCH_MULTIMODEL",
                      "0" if backend_fallback else "1") != "0":
        out["multimodel"] = _bench_multimodel(booster, X)

    # streaming-ingest section (docs/DATA.md): rows/s + the peak-RSS
    # bound proving the raw float matrix never materialized.  At
    # BENCH_ROWS=10500000 this is the Higgs-scale ingest entry.
    if os.environ.get("BENCH_INGEST", "0" if backend_fallback else "1") != "0":
        out["ingest"] = _bench_ingest(X, y, n_rows)

    # checkpoint section (docs/CHECKPOINT.md): save latency + the
    # per-iteration cost of fault tolerance at freq 0/10/1
    if os.environ.get("BENCH_CKPT", "0" if backend_fallback else "1") != "0":
        out["checkpoint"] = _bench_checkpoint(X, y, params)

    # out-of-core section (docs/DATA.md): streamed vs resident s/iter,
    # prefetch overlap, bounded residency — the chunk-streaming cost line
    if os.environ.get("BENCH_OOC", "0" if backend_fallback else "1") != "0":
        out["out_of_core"] = _bench_ooc(X, y, params)

    # factory section (docs/FACTORY.md): append->promoted e2e latency of
    # one warm-started continuous-training cycle, warm-start cost vs the
    # tree-count-matched cold retrain, canary-window plumbing overhead
    if os.environ.get("BENCH_FACTORY", "0" if backend_fallback else "1") != "0":
        out["factory"] = _bench_factory(X, y)

    # serving-tail section (docs/ROBUSTNESS.md): hedged vs unhedged
    # client p99 through a 3-replica fleet with one delay-injected
    # replica.  Runs even on backend_fallback: the injected delay
    # dominates, so the hedged-chaos-over-healthy ratio is a
    # device-independent leg of the regression gate.
    if os.environ.get("BENCH_SERVING_TAIL", "1") != "0":
        out["serving_tail"] = _bench_serving_tail(booster, X)

    # comms section (docs/PARALLEL.md): bytes/iter + s/iter of the
    # data/feature/voting distributed learners on a >=2000-feature
    # synthetic.  Runs even on backend_fallback: the payload numbers are
    # protocol arithmetic, and the voting-vs-data ratio is the
    # device-independent leg of the regression gate.
    if os.environ.get("BENCH_COMMS", "1") != "0":
        out["comms"] = _bench_comms()

    # elastic section (docs/ROBUSTNESS.md): straggler A/B over real
    # 2-rank subprocess fleets — s/iter {no-straggler, straggler with
    # rebalance off, straggler with rebalance on} and the recovery
    # ratio.  Runs even on backend_fallback: the injected stall
    # dominates on any backend, so the >=1.3x recovery contract is the
    # device-independent leg of the regression gate.
    if os.environ.get("BENCH_ELASTIC", "1") != "0":
        out["elastic"] = _bench_elastic()

    # spot-economics section (docs/FACTORY.md): elastic 2-member fleet
    # under a scripted 2-preemption trace vs the static on-demand
    # reference — cost-per-model ratio, resize-pause p50/p99, and the
    # zero-lost-iterations proof.  Runs even on backend_fallback: the
    # cost ratio is price arithmetic, the device-independent leg of the
    # regression gate.
    if os.environ.get("BENCH_SPOT", "1") != "0":
        out["spot"] = _bench_spot()

    # distributed out-of-core section (docs/DATA.md): 2-rank streaming
    # fleets at two chunk grids + the quantized byte-parity contract.
    # Runs even on backend_fallback: integer-fold associativity is
    # protocol arithmetic, the device-independent leg of the gate.
    if os.environ.get("BENCH_OOCDIST", "1") != "0":
        out["ooc_distributed"] = _bench_ooc_distributed()

    # kernel A/B section (docs/PERFORMANCE.md): the PR-6 kernel wins
    # measured head-to-head WITH parity checks — on a dead tunnel this is
    # the evidence the s/iter headline cannot provide
    if os.environ.get("BENCH_KERNEL_AB", "1") != "0":
        out["kernel_ab"] = _bench_kernel_ab()

    # run-trace embedding (docs/OBSERVABILITY.md): the per-phase span
    # totals and compile accounting gathered during THIS run, so the
    # BENCH_*.json line finally explains its own s/iter number
    if tracer.enabled:
        snap = tracer.snapshot()
        out["trace_path"] = tracer.path
        out["phase_breakdown"] = snap["spans"]
        cw = compilewatch.snapshot()
        out["compile_stats"] = {
            "backend_compiles": cw["backend_compiles"],
            "backend_compile_secs": cw["backend_compile_secs"],
            "retraces_flagged": sum(
                w["retraces"] for w in cw["watched"].values()
            ),
        }
        # Prometheus dump next to the trace (docs/OBSERVABILITY.md): the
        # same registry the serve front end scrapes, frozen at end of
        # bench — every mirrored trace counter/gauge + compile totals
        from lightgbm_tpu.obs.metrics import registry as _metrics_registry

        metrics_path = tracer.path + ".metrics.txt"
        try:
            _metrics_registry.dump(metrics_path)
            out["metrics_path"] = metrics_path
        except OSError:
            pass
        # HLO cost model (obs/costmodel.py): the jax_cost program
        # inventory joined against the measured phase spans — per-phase
        # efficiency vs the roofline, and the machine-picked next
        # kernel target (the line ROADMAP item 1 asks every capture to
        # end with)
        from lightgbm_tpu.obs import costmodel

        cm = costmodel.process_summary()
        out["cost_model"] = cm
        for row in cm["table"]:
            if row.get("efficiency_pct") is not None:
                tracer.gauge("cost.efficiency_pct", row["efficiency_pct"],
                             phase=row["phase"], program=row["program"])
        if cm.get("next_target_line"):
            print("# " + cm["next_target_line"], file=sys.stderr)

    # device memory footprint (validates the no-scratch-copy design at
    # Higgs scale; axon may not expose memory_stats — best-effort)
    try:
        ms = jax.local_devices()[0].memory_stats()
        if ms and "bytes_in_use" in ms:
            out["device_mb_in_use"] = round(ms["bytes_in_use"] / 1e6, 1)
            if "peak_bytes_in_use" in ms:
                out["device_mb_peak"] = round(ms["peak_bytes_in_use"] / 1e6, 1)
    except Exception:
        pass

    # perf regression gate: >10% slower than the best comparable prior
    # BENCH_r*.json => "regression": true + nonzero exit (BENCH_GATE=0
    # opts out; silent skip when no prior parses)
    rc = apply_regression_gate(out)
    print(json.dumps(out))
    if rc:
        print("# REGRESSION: s/iter is >10% above the best prior capture "
              f"({out['gate']['best_prior_source']}: "
              f"{out['gate']['best_prior_s_per_iter']} s/iter)",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
