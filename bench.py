"""Benchmark harness — establishes the BASELINE.md north-star metric:
sec/iteration on Higgs-shaped data (docs/GPU-Performance.md:101-117 config:
max_bin=63, num_leaves=255, learning_rate=0.1, min_data_in_leaf=1,
min_sum_hessian_in_leaf=100).

The real Higgs download is unavailable (zero egress), so a synthetic
Higgs-shaped dataset is generated: N x 28 features with the same binary
task structure.  Rows default to 1M (vs Higgs 10.5M) to keep the harness
under a few minutes; the per-iteration time scales linearly in N, so
`vs_baseline` is computed on the measured config.

Prints ONE JSON line: {"metric": ..., "value": ..., "unit": ...,
"vs_baseline": ...}.
"""

import json
import os
import sys
import time

import numpy as np


def make_higgs_shaped(n_rows: int, n_features: int = 28, seed: int = 7):
    """Synthetic binary data with Higgs-like geometry: a few informative
    features plus derived/noisy ones, mildly non-linear decision surface."""
    rng = np.random.RandomState(seed)
    n_inform = 8
    w = rng.randn(n_inform)
    X = rng.randn(n_rows, n_features).astype(np.float32)
    margin = X[:, :n_inform] @ w + 0.5 * X[:, 0] * X[:, 1] - 0.3 * X[:, 2] ** 2
    prob = 1.0 / (1.0 + np.exp(-margin / margin.std()))
    y = (rng.rand(n_rows) < prob).astype(np.float32)
    return X, y


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    n_iters = int(os.environ.get("BENCH_ITERS", 20))
    warmup = 3

    import lightgbm_tpu as lgb
    from lightgbm_tpu.basic import Booster, Dataset

    X, y = make_higgs_shaped(n_rows)
    params = {
        "objective": "binary",
        "metric": "auc",
        "max_bin": 63,
        "num_leaves": 255,
        "learning_rate": 0.1,
        "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100,
        "verbose": -1,
    }
    t0 = time.time()
    ds = Dataset(X, label=y, params=dict(params))
    booster = Booster(params=params, train_set=ds)
    prep_s = time.time() - t0

    # warmup: trigger all XLA compiles
    t0 = time.time()
    for _ in range(warmup):
        booster.update()
    import jax

    jax.block_until_ready(booster.boosting.scores)
    warmup_s = time.time() - t0

    t0 = time.time()
    for _ in range(n_iters):
        booster.update()
    jax.block_until_ready(booster.boosting.scores)
    train_s = time.time() - t0
    sec_per_iter = train_s / n_iters

    # quality signal on held-out synthetic rows
    Xt, yt = make_higgs_shaped(100_000, seed=11)
    prob = booster.predict(Xt)
    from lightgbm_tpu.metric.binary import AUCMetric
    from lightgbm_tpu.config import Config

    m = AUCMetric(Config())

    class _Meta:
        label = yt
        weights = None

    m.init(_Meta, len(yt))
    auc = m.eval(prob)[0][1]

    # vs_baseline: the reference GPU (GTX 1080) trains Higgs-10.5M at about
    # 0.58 s/iter at this config (docs/GPU-Performance.md external chart,
    # commonly-cited ~290 s / 500 iters); scale to the measured row count.
    ref_gpu_sec_per_iter_higgs = 0.58
    ref_scaled = ref_gpu_sec_per_iter_higgs * (n_rows / 10_500_000)
    vs_baseline = ref_scaled / sec_per_iter if sec_per_iter > 0 else 0.0

    print(json.dumps({
        "metric": f"sec/iteration (binary, {n_rows}x28, max_bin=63, num_leaves=255)",
        "value": round(sec_per_iter, 4),
        "unit": "s/iter",
        "vs_baseline": round(vs_baseline, 3),
        "auc_23iters": round(auc, 5),
        "prep_s": round(prep_s, 2),
        "warmup_s": round(warmup_s, 2),
        "device": str(jax.devices()[0]).split(":")[0],
    }))


if __name__ == "__main__":
    sys.exit(main())
