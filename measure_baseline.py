"""Measure the reference binary's same-box CPU sec/iteration and record
it in BASELINE.json "published".

BASELINE.md's wall-clock numbers exist only as an external chart image
(docs/GPU-Performance.md:150), so the only measurable same-box anchor is
the reference CPU build (refbuild/lightgbm, built from /root/reference by
tests/golden/make_goldens.sh's recipe) on the bench harness's own 1M
synthetic at the benchmark config (max_bin=63, num_leaves=255).

Protocol: wall-clock a LONG run (50 iters) and a SHORT run (2 iters) with
identical data/config; (long - short) / 48 removes data loading/binning
from the per-iteration number.  NOTE this box exposes a single CPU core
(nproc=1); the published reference numbers are 28-thread, so the stored
value is labeled with the thread count and is NOT comparable to the
28-core figures — bench.py reports it as "vs_ref_cpu_same_box" alongside
(not replacing) the chart-derived GPU estimate.
"""

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.abspath(__file__))
BIN = os.path.join(ROOT, "refbuild", "lightgbm")
TRAIN = os.path.join(ROOT, "refbuild", "bench_1m.train")

CONF = [
    "task=train",
    "objective=binary",
    "data=" + TRAIN,
    "max_bin=63",
    "num_leaves=255",
    "learning_rate=0.1",
    "min_data_in_leaf=1",
    "min_sum_hessian_in_leaf=100",
    "verbosity=-1",
    "is_training_metric=false",
    "output_model=/dev/null",
]


def ensure_inputs():
    if not os.path.exists(BIN):
        sys.exit(f"missing {BIN} — build with tests/golden/make_goldens.sh recipe")
    if not os.path.exists(TRAIN):
        sys.path.insert(0, ROOT)
        import numpy as np
        import pandas as pd

        from bench import make_higgs_shaped

        X, y = make_higgs_shaped(1_000_000, seed=7)
        pd.DataFrame(np.column_stack([y, X])).to_csv(
            TRAIN, sep="\t", header=False, index=False, float_format="%.6g"
        )


def timed_run(num_trees: int, threads: int) -> float:
    t0 = time.time()
    subprocess.run(
        [BIN] + CONF + [f"num_trees={num_trees}", f"num_threads={threads}"],
        check=True, capture_output=True,
    )
    return time.time() - t0


def main():
    ensure_inputs()
    threads = int(os.environ.get("BASELINE_THREADS", os.cpu_count() or 1))
    long_n = int(os.environ.get("BASELINE_ITERS", 50))
    short_n = 2
    t_short = timed_run(short_n, threads)
    t_long = timed_run(long_n, threads)
    sec_per_iter = (t_long - t_short) / (long_n - short_n)
    print(f"short({short_n})={t_short:.1f}s long({long_n})={t_long:.1f}s "
          f"-> {sec_per_iter:.4f} s/iter @ {threads} threads")

    path = os.path.join(ROOT, "BASELINE.json")
    with open(path) as f:
        base = json.load(f)
    base.setdefault("published", {})
    base["published"].update({
        "ref_cpu_sec_per_iter_1m_rows": round(sec_per_iter, 4),
        "ref_cpu_threads": threads,
        "ref_cpu_iters_timed": long_n - short_n,
        "ref_cpu_note": (
            "same-box CPU measurement on bench.py's 1M synthetic; this box "
            "has nproc=1 so NOT comparable to the 28-thread published runs"
        ),
    })
    with open(path, "w") as f:
        json.dump(base, f, indent=2)
    print(f"recorded in {path}")


if __name__ == "__main__":
    main()
